"""Parameter-server training (reference: paddle/fluid/distributed/ps/ —
brpc PsService, table/ (dense + sparse accessor tables, server-side
optimizers), and the fleet PS role flow: fleet.init(role) ->
init_server()/run_server() on PSERVER nodes, init_worker() + pull/push
on TRAINER nodes).

TPU-native redesign, not a port: on a TPU pod the DENSE model is
synchronous SPMD (sharded on the mesh — see DESIGN.md), so the PS role
that survives is the one brpc exists for: EMBEDDING TABLES TOO BIG FOR
HBM, held on host servers, with trainers pulling the rows a batch needs
and pushing sparse gradients back. That is exactly what this module
provides:

- :class:`PsServer` — a host service holding table SHARDS (row id %
  num_servers), applying server-side optimizers (sgd/adagrad/adam) under
  a per-table lock on each push (async by default; ``barrier`` gives
  sync-mode edges). Transport is length-prefixed pickles over TCP
  sockets on a trusted cluster network — the data plane the reference
  implements in brpc C++; the accept loop and table math are numpy.
- :class:`PsClient` — trainer-side handle: ``pull_sparse(table, ids)``,
  ``push_sparse(table, ids, grads)``, dense pull/push, barrier, save.
- :class:`DistributedEmbedding` — the `paddle.static.nn.sparse_embedding`
  analog: forward pulls rows onto the device, backward pushes the sparse
  grad rows from the autograd hook.

Row sharding across servers means each server owns 1/S of every table;
lookups fan out only to the servers owning the requested rows.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["PsServer", "PsClient", "DistributedEmbedding", "TableConfig"]


# ---------------------------------------------------------------------------
# wire protocol: [u32 length][pickle (cmd, payload)] -> same shape response
# ---------------------------------------------------------------------------


def _send(sock: socket.socket, obj) -> None:
    blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<I", len(blob)) + blob)


def _recv(sock: socket.socket):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (n,) = struct.unpack("<I", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return pickle.loads(bytes(buf))


def _sm64(x: np.ndarray) -> np.ndarray:
    """splitmix64 over uint64 numpy arrays — the row-init hash SHARED
    with the native data plane (native/src/ps_table.cc::sm64); both
    planes must produce bit-identical rows so tables are interchangeable
    (cross-plane parity is tested)."""
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def _hash_uniform(seed: int, server_idx: int, rid: int, dim: int,
                  init_range: float) -> np.ndarray:
    """Deterministic uniform[-r, r) row, portable across planes: float64
    from the top 53 bits of splitmix64, cast to float32 (matches the C++
    double path exactly)."""
    base = _sm64(np.asarray([np.uint64(
        (seed * 1000003 + server_idx) & 0xFFFFFFFFFFFFFFFF)],
        np.uint64))[0]
    h0 = _sm64(np.asarray([base ^ np.uint64(rid & 0xFFFFFFFFFFFFFFFF)],
                          np.uint64))[0]
    with np.errstate(over="ignore"):
        v = _sm64(h0 + np.arange(dim, dtype=np.uint64))
    u = (v >> np.uint64(11)).astype(np.float64) * (1.0 / (1 << 53))
    # init_range through float32 first: the native plane's TableCfg
    # carries it as f32 on the wire, and bit-parity requires multiplying
    # by the same double (double(float(r)) != double(r) for e.g. 0.1)
    r = np.float64(np.float32(init_range))
    return ((2.0 * u - 1.0) * r).astype(np.float32)


class TableConfig:
    """One table's schema + server-side optimizer (reference
    ps/table/ctr_accessor + sparse_sgd_rule: the optimizer runs ON the
    server at push time)."""

    def __init__(self, name: str, dim: int, optimizer: str = "sgd",
                 lr: float = 0.01, initializer: str = "uniform",
                 init_range: float = 0.1, seed: int = 0,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, entry=None):
        self.name = name
        self.dim = int(dim)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.initializer = initializer
        self.init_range = float(init_range)
        self.seed = int(seed)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        # EntryAttr admission policy (distributed.ProbabilityEntry /
        # CountFilterEntry / ShowClickEntry — reference entry_attr.py);
        # None = plain embedding semantics (rows admitted on first touch)
        self.entry = entry


class _SparseShard:
    """This server's rows of one sparse table: id -> (row, opt slots),
    created on first touch (the reference's on-demand CTR table rows)."""

    def __init__(self, cfg: TableConfig, server_idx: int):
        self.cfg = cfg
        self.server_idx = int(server_idx)
        self.rows: Dict[int, np.ndarray] = {}
        self.slots: Dict[int, tuple] = {}
        self.counts: Dict[int, int] = {}        # CountFilterEntry
        self.rejected: set = set()              # ProbabilityEntry
        self.show_click: Dict[int, list] = {}   # ShowClickEntry stats
        self.step = 0
        self._seed = (cfg.seed * 1000003 + server_idx) & 0x7FFFFFFF
        self.lock = threading.Lock()

    def _init_row(self, rid: int) -> np.ndarray:
        if self.cfg.initializer == "zeros":
            return np.zeros((self.cfg.dim,), np.float32)
        # hash-based uniform shared bit-for-bit with the native plane
        return _hash_uniform(self.cfg.seed, self.server_idx, rid,
                             self.cfg.dim, self.cfg.init_range)

    def _admit(self, rid: int) -> bool:
        """Entry-admission policy for an ABSENT row at push time
        (reference CTR accessor + entry_attr): ProbabilityEntry draws
        once per row (deterministic in (seed, rid)); CountFilterEntry
        requires count_filter occurrences first."""
        entry = self.cfg.entry
        attr = getattr(entry, "_to_attr", lambda: "")()
        if attr.startswith("probability_entry"):
            if rid in self.rejected:
                return False
            p = entry._probability
            draw = np.random.RandomState(
                (self._seed ^ (rid * 2654435761)) & 0x7FFFFFFF).rand()
            if draw >= p:
                self.rejected.add(rid)
                return False
            return True
        if attr.startswith("count_filter_entry"):
            c = self.counts.get(rid, 0) + 1
            self.counts[rid] = c
            return c >= entry._count_filter
        return True

    def pull(self, ids: np.ndarray) -> np.ndarray:
        gated = self.cfg.entry is not None
        with self.lock:
            out = np.empty((len(ids), self.cfg.dim), np.float32)
            for i, rid in enumerate(ids):
                rid = int(rid)
                if rid not in self.rows:
                    if gated:
                        # entry policies admit on PUSH; unadmitted rows
                        # read as zeros and are not stored
                        out[i] = 0.0
                        continue
                    self.rows[rid] = self._init_row(rid)
                out[i] = self.rows[rid]
            return out

    def push_show_click(self, ids, shows, clicks):
        with self.lock:
            for rid, sh, ck in zip(ids, shows, clicks):
                rec = self.show_click.setdefault(int(rid), [0.0, 0.0])
                rec[0] += float(sh)
                rec[1] += float(ck)

    def pull_show_click(self, ids):
        with self.lock:
            return np.asarray([self.show_click.get(int(r), [0.0, 0.0])
                               for r in ids], np.float32)

    def push(self, ids: np.ndarray, grads: np.ndarray) -> None:
        cfg = self.cfg
        with self.lock:
            self.step += 1
            for rid, g in zip(ids, grads):
                rid = int(rid)
                w = self.rows.get(rid)
                if w is None:
                    if cfg.entry is not None and not self._admit(rid):
                        continue
                    w = self.rows[rid] = self._init_row(rid)
                if cfg.optimizer == "sgd":
                    w -= cfg.lr * g
                elif cfg.optimizer == "adagrad":
                    acc = self.slots.get(rid)
                    acc = acc[0] if acc else np.zeros_like(w)
                    acc += g * g
                    self.slots[rid] = (acc,)
                    w -= cfg.lr * g / (np.sqrt(acc) + cfg.epsilon)
                elif cfg.optimizer == "adam":
                    m, v, t = self.slots.get(
                        rid, (np.zeros_like(w), np.zeros_like(w), 0))
                    t += 1
                    m = cfg.beta1 * m + (1 - cfg.beta1) * g
                    v = cfg.beta2 * v + (1 - cfg.beta2) * g * g
                    mh = m / (1 - cfg.beta1 ** t)
                    vh = v / (1 - cfg.beta2 ** t)
                    w -= cfg.lr * mh / (np.sqrt(vh) + cfg.epsilon)
                    self.slots[rid] = (m, v, t)
                else:
                    raise ValueError(
                        f"unknown server optimizer {cfg.optimizer!r}")


class PsServer:
    """One parameter-server node. ``start()`` returns immediately (the
    accept loop runs on threads — reference PsService handlers);
    ``run()`` blocks until a client sends STOP (reference
    fleet.run_server).

    .. warning:: TRUSTED NETWORKS ONLY. This plane's transport is
       pickle-over-TCP: anyone who can reach the port can execute code
       in this process via a crafted pickle. Bind it on a private
       cluster interface only. Plain tables (no entry-admission /
       show-click accessors) should use the native binary-protocol
       plane instead (``distributed.ps.native``, the default under
       ``fleet.init_server`` when the toolchain is available)."""

    def __init__(self, server_idx: int, num_servers: int, port: int = 0,
                 host: str = "127.0.0.1"):
        import warnings

        warnings.warn(
            "PsServer's Python data plane unpickles from its TCP port — "
            "trusted cluster networks only (use the native plane, "
            "PADDLE_PS_DATA_PLANE=native, for plain tables)",
            RuntimeWarning, stacklevel=2)
        self.server_idx = int(server_idx)
        self.num_servers = int(num_servers)
        self._tables: Dict[str, _SparseShard] = {}
        self._dense: Dict[str, np.ndarray] = {}
        self._dense_lock = threading.Lock()
        self._barrier_count: Dict[str, int] = {}
        self._barrier_lock = threading.Lock()
        self._barrier_cv = threading.Condition(self._barrier_lock)
        self._stop = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()

    # -- service ------------------------------------------------------------
    def start(self):
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        return self

    def run(self):
        """Block until stopped (reference fleet.run_server)."""
        self._accept_loop_started = True
        self.start()
        self._stop.wait()

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _serve_one(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                cmd, payload = _recv(conn)
                try:
                    resp = ("ok", self._dispatch(cmd, payload))
                except Exception as e:  # noqa: BLE001 - ship to client
                    resp = ("err", f"{type(e).__name__}: {e}")
                _send(conn, resp)
                if cmd == "stop":
                    self.stop()
                    return
        except ConnectionError:
            pass
        finally:
            conn.close()

    def _dispatch(self, cmd: str, p):
        if cmd == "create_table":
            cfg = p
            shard = self._tables.get(cfg.name)
            if shard is None:
                self._tables[cfg.name] = _SparseShard(cfg, self.server_idx)
            else:
                # table exists (e.g. rows restored by load_model under a
                # default config): ADOPT the caller's config, keep rows —
                # otherwise a resumed run silently trains with sgd/lr=0.01
                if shard.cfg.dim != cfg.dim:
                    raise ValueError(
                        f"table {cfg.name!r} exists with dim "
                        f"{shard.cfg.dim}, cannot adopt dim {cfg.dim}")
                with shard.lock:
                    shard.cfg = cfg
                    # derived admission seed must follow the adopted cfg
                    # (ProbabilityEntry draws are 'deterministic in
                    # (seed, rid)' — a stale _seed would break that)
                    shard._seed = (cfg.seed * 1000003
                                   + shard.server_idx) & 0x7FFFFFFF
            return True
        if cmd == "pull_sparse":
            return self._tables[p["table"]].pull(p["ids"])
        if cmd == "push_sparse":
            self._tables[p["table"]].push(p["ids"], p["grads"])
            return True
        if cmd == "push_show_click":
            self._tables[p["table"]].push_show_click(
                p["ids"], p["shows"], p["clicks"])
            return True
        if cmd == "pull_show_click":
            return self._tables[p["table"]].pull_show_click(p["ids"])
        if cmd == "init_dense":
            with self._dense_lock:
                self._dense.setdefault(p["name"], np.array(p["value"],
                                                           np.float32))
            return True
        if cmd == "pull_dense":
            with self._dense_lock:
                return self._dense[p["name"]]
        if cmd == "push_dense":
            with self._dense_lock:
                self._dense[p["name"]] -= p["lr"] * p["grad"]
            return True
        if cmd == "barrier":
            return self._barrier(p["name"], p["world"])
        if cmd == "save":
            return self._save(p["dirname"])
        if cmd == "stats":
            return {name: len(t.rows) for name, t in self._tables.items()}
        if cmd == "stop":
            return True
        raise ValueError(f"unknown PS command {cmd!r}")

    def _barrier(self, name: str, world: int):
        """Returns this caller's ARRIVAL POSITION in the generation
        (1..world) — position == world identifies the last arrival, the
        one allowed to run post-barrier teardown (stop_worker)."""
        with self._barrier_cv:
            self._barrier_count[name] = self._barrier_count.get(name, 0) + 1
            count = self._barrier_count[name]
            pos = (count - 1) % world + 1
            target = ((count - 1) // world + 1) * world
            while self._barrier_count[name] < target \
                    and not self._stop.is_set():
                self._barrier_cv.wait(timeout=0.1)
            self._barrier_cv.notify_all()
            return pos

    def _save(self, dirname: str):
        os.makedirs(dirname, exist_ok=True)
        for name, t in self._tables.items():
            with t.lock:
                ids = np.fromiter(t.rows.keys(), np.int64,
                                  count=len(t.rows))
                vals = (np.stack([t.rows[int(i)] for i in ids])
                        if len(ids) else
                        np.zeros((0, t.cfg.dim), np.float32))
            np.savez(os.path.join(
                dirname, f"{name}.shard{self.server_idx}.npz"),
                ids=ids, values=vals)
        return True

    def load_model(self, dirname: str):
        """Restore THIS shard's rows from a prior ``save`` (reference
        fleet.init_server(dirname) loads the saved model)."""
        import glob

        suffix = f".shard{self.server_idx}.npz"
        found = glob.glob(os.path.join(dirname, f"*{suffix}"))
        other = glob.glob(os.path.join(
            dirname, f"*.shard{self.server_idx}.psbin"))
        if not found and other:
            raise ValueError(
                f"{dirname} holds NATIVE-plane saves (.psbin) — the save "
                "formats are per-plane. Restore with "
                "PADDLE_PS_DATA_PLANE=native, or run "
                "distributed.ps.native.convert_save(dirname, to='python') "
                "first")
        for path in found:
            name = os.path.basename(path)[: -len(suffix)]
            data = np.load(path)
            ids, vals = data["ids"], data["values"]
            shard = self._tables.get(name)
            if shard is None:
                dim = int(vals.shape[1]) if vals.ndim == 2 else 0
                shard = self._tables[name] = _SparseShard(
                    TableConfig(name, dim), self.server_idx)
            with shard.lock:
                for i, rid in enumerate(ids):
                    shard.rows[int(rid)] = vals[i].astype(np.float32)
        return self


class PsClient:
    """Trainer-side handle to the server group (reference brpc_ps_client).
    Row routing: id % num_servers picks the owning shard."""

    def __init__(self, endpoints: Sequence[str]):
        self.endpoints = list(endpoints)
        self._socks: List[socket.socket] = []
        self._locks: List[threading.Lock] = []
        for ep in self.endpoints:
            host, port = ep.rsplit(":", 1)
            s = socket.create_connection((host, int(port)), timeout=30)
            self._socks.append(s)
            self._locks.append(threading.Lock())

    def _call(self, idx: int, cmd: str, payload):
        with self._locks[idx]:
            _send(self._socks[idx], (cmd, payload))
            status, resp = _recv(self._socks[idx])
        if status != "ok":
            raise RuntimeError(f"PS server {idx}: {resp}")
        return resp

    def _all(self, cmd: str, payload):
        return [self._call(i, cmd, payload)
                for i in range(len(self._socks))]

    # -- tables --------------------------------------------------------------
    def create_table(self, cfg: TableConfig):
        self._all("create_table", cfg)

    def pull_sparse(self, table: str, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).ravel()
        n = len(self._socks)
        if ids.size == 0:
            return np.empty((0, 0), np.float32)
        parts = []
        for s in range(n):
            mask = (ids % n) == s
            if not mask.any():
                parts.append(None)
                continue
            rows = self._call(s, "pull_sparse",
                              {"table": table, "ids": ids[mask]})
            parts.append((mask, rows))
        dim = next(p[1].shape[1] for p in parts if p is not None)
        out = np.empty((ids.size, dim), np.float32)
        for p in parts:
            if p is not None:
                out[p[0]] = p[1]
        return out

    def push_sparse(self, table: str, ids, grads) -> None:
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(ids.size, -1)
        n = len(self._socks)
        for s in range(n):
            mask = (ids % n) == s
            if mask.any():
                self._call(s, "push_sparse",
                           {"table": table, "ids": ids[mask],
                            "grads": grads[mask]})

    def push_show_click(self, table: str, ids, shows, clicks) -> None:
        """Accumulate CTR stats for a ShowClickEntry table."""
        ids = np.asarray(ids, np.int64).ravel()
        shows = np.asarray(shows, np.float32).ravel()
        clicks = np.asarray(clicks, np.float32).ravel()
        n = len(self._socks)
        for s in range(n):
            mask = (ids % n) == s
            if mask.any():
                self._call(s, "push_show_click",
                           {"table": table, "ids": ids[mask],
                            "shows": shows[mask], "clicks": clicks[mask]})

    def pull_show_click(self, table: str, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).ravel()
        n = len(self._socks)
        out = np.zeros((ids.size, 2), np.float32)
        for s in range(n):
            mask = (ids % n) == s
            if mask.any():
                out[mask] = self._call(s, "pull_show_click",
                                       {"table": table, "ids": ids[mask]})
        return out

    # -- dense ---------------------------------------------------------------
    def init_dense(self, name: str, value) -> None:
        # dense params live on server 0 (small: biases/stats; the big
        # dense model is mesh-sharded SPMD, not PS-served — DESIGN.md)
        self._call(0, "init_dense", {"name": name, "value": value})

    def pull_dense(self, name: str) -> np.ndarray:
        return self._call(0, "pull_dense", {"name": name})

    def push_dense(self, name: str, grad, lr: float = 0.01) -> None:
        self._call(0, "push_dense", {"name": name, "grad": grad, "lr": lr})

    # -- control -------------------------------------------------------------
    def barrier(self, name: str = "default", world: int = 1):
        return self._call(0, "barrier", {"name": name, "world": world})

    def save(self, dirname: str):
        return self._all("save", {"dirname": dirname})

    def stats(self):
        return self._all("stats", None)

    def stop_servers(self):
        for i in range(len(self._socks)):
            try:
                self._call(i, "stop", None)
            except (RuntimeError, ConnectionError):
                pass

    def close(self):
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass


class DistributedEmbedding:
    """`paddle.static.nn.sparse_embedding` analog: an embedding whose
    table lives on the parameter servers. The device only ever holds the
    rows a batch touches — tables may exceed HBM by orders of magnitude.

    Eager (paddle Tensor) usage: ``rows = emb(ids_tensor)`` pulls the
    rows and registers a gradient HOOK, so ``loss.backward()`` pushes the
    per-row sparse gradient to the servers automatically (server-side
    optimize — the reference accessor flow). Functional/jit usage is the
    explicit pair ``rows = emb.pull(ids)`` ... ``emb.push(ids, grad)``
    with the cotangent from ``jax.grad`` w.r.t. ``rows``."""

    def __init__(self, client: PsClient, name: str, dim: int,
                 optimizer: str = "sgd", lr: float = 0.01, **cfg_kw):
        self.client = client
        self.name = name
        self.dim = dim
        client.create_table(TableConfig(name, dim, optimizer=optimizer,
                                        lr=lr, **cfg_kw))

    def pull(self, ids) -> np.ndarray:
        flat = np.asarray(ids, np.int64).ravel()
        rows = self.client.pull_sparse(self.name, flat)
        return rows.reshape(tuple(np.shape(ids)) + (self.dim,))

    def push(self, ids, grads) -> None:
        flat = np.asarray(ids, np.int64).ravel()
        self.client.push_sparse(self.name, flat,
                                np.asarray(grads).reshape(flat.size, -1))

    def __call__(self, ids):
        import jax.numpy as jnp

        from ...core.tensor import Tensor

        raw = ids.value if isinstance(ids, Tensor) else ids
        rows = self.pull(np.asarray(raw))
        if not isinstance(ids, Tensor):
            return jnp.asarray(rows)
        out = Tensor(jnp.asarray(rows), stop_gradient=False)
        flat = np.asarray(raw, np.int64).ravel()
        client, name = self.client, self.name

        def _push_hook(g):
            client.push_sparse(
                name, flat,
                np.asarray(g.value).reshape(flat.size, -1))
            return None                 # keep the grad unchanged

        out.register_hook(_push_hook)
        return out
