"""Native (C++) parameter-server data plane — ctypes over
``native/src/ps_table.cc``.

Reference analog: the brpc data plane (brpc_ps_server.cc /
brpc_ps_client.cc) under the same fleet role flow. This plane carries
the HOT path — plain embedding tables with server-side optimizers,
binary wire protocol, zero pickling — and is row-init bit-identical to
the Python plane (shared splitmix64 hash), so the two produce
interchangeable tables. Feature split, documented:

- native: sparse pull/push (sgd/adagrad/adam server-side), dense
  init/pull/push, barrier, save/load (``.psbin``), stats, stop.
- python plane only: entry-admission policies (Probability/CountFilter/
  ShowClick) and show/click accessors — ``create_table`` here raises on
  ``cfg.entry`` and points at the Python plane.

This plane is the DEFAULT under the fleet ``init_server``/
``init_worker`` flow whenever the toolchain builds it;
``PADDLE_PS_DATA_PLANE`` (``native``/``python``) pins the choice and
must be set identically on every node — mixing planes within one
server group is not supported (and fails with opaque stream errors).
"""
from __future__ import annotations

import ctypes
import os
import time
from typing import List, Sequence

import numpy as np

from . import TableConfig

__all__ = ["NativePsServer", "NativePsClient", "convert_save"]

_OPT_IDS = {"sgd": 0, "adagrad": 1, "adam": 2}


def _lib():
    from ...native import _load

    lib = _load()
    if lib is None:
        raise RuntimeError(
            "native library unavailable (g++ build failed?) — use the "
            "Python data plane (distributed.ps.PsServer)")
    return lib


class NativePsServer:
    """One native PS shard. API mirrors ``PsServer`` (start/run/stop,
    ``load_model``); table state lives in C++."""

    def __init__(self, server_idx: int, num_servers: int, port: int = 0,
                 host: str = "127.0.0.1"):
        self._lib = _lib()
        self.server_idx = int(server_idx)
        self.num_servers = int(num_servers)
        self.host = host
        self._h = self._lib.pst_server_start(port, self.server_idx,
                                             host.encode())
        if not self._h:
            raise OSError(f"cannot bind native PS server on port {port}")
        self.port = int(self._lib.pst_server_port(self._h))
        self._stopped = False

    def start(self):
        return self  # accept loop already runs on native threads

    def run(self):
        """Block until a client sends STOP (reference fleet.run_server)."""
        while not self._lib.pst_server_stopped(self._h):
            time.sleep(0.05)

    def stop(self):
        if not self._stopped:
            self._stopped = True
            self._lib.pst_server_stop(self._h)

    def load_model(self, dirname: str, tables: Sequence[TableConfig] = ()):
        """Restore this shard's rows from ``.psbin`` files written by
        ``NativePsClient.save``. Table names are discovered from the
        directory (reference init_server(dirname) contract); pass
        ``tables`` to also restore each table's optimizer config."""
        import glob

        cfg_by_name = {c.name: c for c in tables}
        suffix = f".shard{self.server_idx}.psbin"
        found = glob.glob(os.path.join(dirname, f"*{suffix}"))
        other = glob.glob(os.path.join(
            dirname, f"*.shard{self.server_idx}.npz"))
        if not found and other:
            raise ValueError(
                f"{dirname} holds PYTHON-plane saves (.npz) — the save "
                "formats are per-plane. Restore with the Python plane, or "
                "run distributed.ps.native.convert_save(dirname, "
                "to='native') first")
        for path in found:
            name = os.path.basename(path)[: -len(suffix)]
            cfg = cfg_by_name.get(name)
            opt = _OPT_IDS[cfg.optimizer] if cfg else 0
            lr = cfg.lr if cfg else 0.01
            rc = self._lib.pst_server_load(
                self._h, dirname.encode(), name.encode(), opt,
                ctypes.c_float(lr))
            if rc < 0:
                raise OSError(f"load_model({name}): native rc={rc}")
        return self

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class NativePsClient:
    """Trainer-side handle over the native wire protocol; same method
    surface as ``PsClient`` for the plain-table subset, same row routing
    (id % num_servers)."""

    def __init__(self, endpoints: Sequence[str]):
        import threading

        self._lib = _lib()
        self.endpoints = list(endpoints)
        self._conns: List = []
        # one request-response at a time per socket (same invariant as
        # the Python PsClient): DistributedEmbedding's backward hook and
        # a prefetch thread may share one client
        self._locks: List = []
        self._dims = {}
        self._dense_sizes = {}
        for ep in self.endpoints:
            host, port = ep.rsplit(":", 1)
            h = self._lib.pst_connect(host.encode(), int(port))
            if not h:
                raise ConnectionError(f"cannot connect native PS at {ep}")
            self._conns.append(h)
            self._locks.append(threading.Lock())

    def _check(self, rc: int, what: str):
        if rc < 0:
            raise RuntimeError(f"native PS {what}: rc={rc}")
        return rc

    # -- tables --------------------------------------------------------------
    def create_table(self, cfg: TableConfig):
        if cfg.entry is not None:
            raise ValueError(
                "entry-admission policies are a Python-data-plane feature "
                "(distributed.ps.PsServer/PsClient); the native plane "
                "serves plain tables")
        if "\n" in cfg.name:
            raise ValueError(
                "native-plane table names cannot contain newlines (the "
                "LIST op is newline-framed); use the Python plane for "
                "such names")
        init_kind = 1 if cfg.initializer == "zeros" else 0
        for h, lk in zip(self._conns, self._locks):
            with lk:
                self._check(self._lib.pst_create_table(
                    h, cfg.name.encode(), cfg.dim, _OPT_IDS[cfg.optimizer],
                    init_kind, cfg.seed & 0xFFFFFFFFFFFFFFFF,
                    ctypes.c_float(cfg.lr), ctypes.c_float(cfg.beta1),
                    ctypes.c_float(cfg.beta2), ctypes.c_float(cfg.epsilon),
                    ctypes.c_float(cfg.init_range)), "create_table")
        self._dims[cfg.name] = cfg.dim

    def pull_sparse(self, table: str, ids) -> np.ndarray:
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).ravel())
        dim = self._dims[table]
        n_srv = len(self._conns)
        out = np.empty((ids.size, dim), np.float32)
        if ids.size == 0:
            return out
        for s in range(n_srv):
            mask = (ids % n_srv) == s
            if not mask.any():
                continue
            part = np.ascontiguousarray(ids[mask])
            rows = np.empty((part.size, dim), np.float32)
            with self._locks[s]:
                self._check(self._lib.pst_pull_sparse(
                    self._conns[s], table.encode(), part.size,
                    part.ctypes.data_as(ctypes.c_void_p),
                    rows.ctypes.data_as(ctypes.c_void_p), dim),
                    "pull_sparse")
            out[mask] = rows
        return out

    def push_sparse(self, table: str, ids, grads) -> None:
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).ravel())
        dim = self._dims[table]
        grads = np.ascontiguousarray(
            np.asarray(grads, np.float32).reshape(ids.size, dim))
        n_srv = len(self._conns)
        for s in range(n_srv):
            mask = (ids % n_srv) == s
            if not mask.any():
                continue
            part = np.ascontiguousarray(ids[mask])
            g = np.ascontiguousarray(grads[mask])
            with self._locks[s]:
                self._check(self._lib.pst_push_sparse(
                    self._conns[s], table.encode(), part.size, dim,
                    part.ctypes.data_as(ctypes.c_void_p),
                    g.ctypes.data_as(ctypes.c_void_p)), "push_sparse")

    # -- dense ---------------------------------------------------------------
    def init_dense(self, name: str, value) -> None:
        v = np.ascontiguousarray(np.asarray(value, np.float32).ravel())
        with self._locks[0]:
            self._check(self._lib.pst_dense_init(
                self._conns[0], name.encode(), v.size,
                v.ctypes.data_as(ctypes.c_void_p)), "init_dense")
        self._dense_sizes[name] = int(v.size)

    def pull_dense(self, name: str) -> np.ndarray:
        # size known from init_dense / a prior pull: exact-size buffer,
        # one round trip. Unknown (another trainer initialized it): probe
        # with cap=0 to learn the size, then fetch.
        cap = self._dense_sizes.get(name, 0)
        got = ctypes.c_uint64(0)
        for _ in range(2):
            out = np.empty((cap,), np.float32)
            with self._locks[0]:
                self._check(self._lib.pst_dense_pull(
                    self._conns[0], name.encode(),
                    out.ctypes.data_as(ctypes.c_void_p), cap,
                    ctypes.byref(got)), "pull_dense")
            n = int(got.value)
            if n <= cap:
                self._dense_sizes[name] = n
                return out[:n]
            cap = n
        raise RuntimeError(f"pull_dense({name}): size changed mid-pull")

    def push_dense(self, name: str, grad, lr: float = 0.01) -> None:
        g = np.ascontiguousarray(np.asarray(grad, np.float32).ravel())
        with self._locks[0]:
            self._check(self._lib.pst_dense_push(
                self._conns[0], name.encode(), ctypes.c_float(lr), g.size,
                g.ctypes.data_as(ctypes.c_void_p)), "push_dense")

    # -- control -------------------------------------------------------------
    def barrier(self, name: str = "default", world: int = 1):
        with self._locks[0]:
            return self._check(self._lib.pst_barrier(
                self._conns[0], name.encode(), world), "barrier")

    def save(self, dirname: str):
        out = []
        for h, lk in zip(self._conns, self._locks):
            with lk:
                out.append(self._check(
                    self._lib.pst_save(h, dirname.encode()), "save"))
        return out

    def _list_tables(self, idx: int):
        cap = 1 << 16
        for _ in range(2):
            buf = ctypes.create_string_buffer(cap)
            got = ctypes.c_uint64(0)
            self._check(self._lib.pst_list_tables(
                self._conns[idx], buf, cap, ctypes.byref(got)),
                "list_tables")
            n = int(got.value)
            if n <= cap:  # ps_request reports the FULL length — a larger
                blob = buf.raw[:n].decode()  # value means truncation
                return [t for t in blob.split("\n") if t]
            cap = n
        raise RuntimeError("list_tables: table set changed mid-listing")

    def stats(self):
        """Row counts per server for EVERY server-side table (same
        semantics as the Python plane — the LIST op discovers tables
        this client did not itself create)."""
        out = []
        for i, (h, lk) in enumerate(zip(self._conns, self._locks)):
            with lk:
                names = self._list_tables(i)
                out.append({t: int(self._check(
                    self._lib.pst_stats(h, t.encode()), "stats"))
                    for t in names})
        return out

    def stop_servers(self):
        for h, lk in zip(self._conns, self._locks):
            try:
                with lk:
                    self._lib.pst_stop(h)
            except Exception:
                pass

    def close(self):
        for h in self._conns:
            try:
                self._lib.pst_close(h)
            except Exception:
                pass
        self._conns = []


def convert_save(dirname: str, to: str) -> list:
    """Convert a PS save directory between plane formats in place:
    ``to="native"`` rewrites every ``*.npz`` shard (Python plane) as
    ``.psbin``; ``to="python"`` the reverse. Returns the written paths.
    Rows only — optimizer slots are not part of either save format (both
    planes re-create them on first push, matching the reference's
    save/load contract)."""
    import glob
    import struct

    def _row_dtype(dim):
        # matches the .psbin row layout: [i64 id][f32 * dim]
        return np.dtype([("id", "<i8"), ("w", "<f4", (dim,))])

    written = []
    if to == "native":
        for path in glob.glob(os.path.join(dirname, "*.shard*.npz")):
            data = np.load(path)
            ids = np.asarray(data["ids"], np.int64)
            vals = np.ascontiguousarray(
                np.asarray(data["values"], np.float32))
            dim = int(vals.shape[1]) if vals.ndim == 2 else 0
            rows = np.empty((len(ids),), _row_dtype(dim))
            rows["id"] = ids
            rows["w"] = vals
            out = path[: -len(".npz")] + ".psbin"
            with open(out, "wb") as f:
                f.write(struct.pack("<IQ", dim, len(ids)))
                rows.tofile(f)  # one vectorized pass — shards are huge
            written.append(out)
    elif to == "python":
        for path in glob.glob(os.path.join(dirname, "*.shard*.psbin")):
            with open(path, "rb") as f:
                dim, n = struct.unpack("<IQ", f.read(12))
                rows = np.fromfile(f, _row_dtype(dim), count=n)
            if len(rows) != n:
                raise ValueError(f"{path}: truncated ({len(rows)}/{n} rows)")
            out = path[: -len(".psbin")] + ".npz"
            np.savez(out, ids=rows["id"].astype(np.int64),
                     values=np.ascontiguousarray(rows["w"]))
            written.append(out)
    else:
        raise ValueError(f"unknown target plane {to!r} (native|python)")
    return written
