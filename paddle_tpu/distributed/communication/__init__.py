"""paddle.distributed communication API (communication/all_reduce.py:19 etc.).

Signatures match the reference; semantics follow the stacked-ranks /
traced-shard contract documented in core.py.
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._helpers import unwrap
from .core import ReduceOp, collective, get_group, in_traced_context, new_group

__all__ = ["ReduceOp", "all_reduce", "all_gather", "all_gather_object",
           "all_to_all", "all_to_all_single", "reduce_scatter", "broadcast",
           "reduce", "scatter", "send", "recv", "isend", "irecv", "barrier",
           "stream"]


class _Task:
    """≙ ProcessGroup::Task (collective/process_group.h) — XLA collectives are
    launched by the compiled program; wait() is a device sync."""

    def __init__(self, result=None):
        self.result = result

    def wait(self):
        if self.result is not None:
            v = self.result.value if isinstance(self.result, Tensor) else self.result
            try:
                v.block_until_ready()
            except AttributeError:
                pass
        return True

    def is_completed(self):
        return True


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    out = collective("all_reduce", tensor, group, extra=(op,))
    if isinstance(tensor, Tensor):
        tensor.set_value(out.value)
    return _Task(out)


def all_gather(tensor_list: Optional[List], tensor, group=None, sync_op=True):
    g = get_group(group)
    if g.axis_name is not None and not isinstance(g.axis_name, tuple) \
            and in_traced_context(g.axis_name):
        out = collective("all_gather_stack", tensor, group)
        if tensor_list is not None:
            for i in range(out.shape[0]):
                tensor_list.append(out[i])
        return _Task(out)
    out = collective("all_gather_stack", tensor, group)
    # stacked eager result: [n_ranks, n_ranks, ...] — every rank sees all
    if tensor_list is not None:
        row = out[0]
        for i in range(row.shape[0]):
            tensor_list.append(row[i])
    return _Task(out)


def all_gather_object(object_list, obj, group=None):
    # single-controller: every "rank" sees the object
    g = get_group(group)
    object_list.extend([obj] * g.nranks)


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    inp = tensor_or_tensor_list
    if isinstance(inp, (list, tuple)):
        from ...ops.manipulation import concat

        inp = concat(list(inp), axis=0)
    out = collective("reduce_scatter", inp, group, extra=(op,))
    if isinstance(tensor, Tensor):
        tensor.set_value(out.value if out.ndim == tensor.ndim
                         else out.value.reshape(tensor.shape))
    return _Task(out)


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    from ...ops.manipulation import concat, split

    if isinstance(in_tensor_list, (list, tuple)):
        inp = concat(list(in_tensor_list), axis=0)
        n = len(in_tensor_list)
    else:
        inp = in_tensor_list
        n = get_group(group).nranks
    out = collective("all_to_all", inp, group)
    if out_tensor_list is not None:
        g = get_group(group)
        axis = g.axis_name
        if axis is not None and not isinstance(axis, tuple) and in_traced_context(axis):
            pieces = split(out, n, axis=0)
        else:
            pieces = split(out[0], n, axis=0) if out.ndim > inp.ndim else \
                split(out, n, axis=0)
        out_tensor_list.extend(pieces)
    return _Task(out)


def all_to_all_single(out_tensor, in_tensor, in_split_sizes=None,
                      out_split_sizes=None, group=None, sync_op=True):
    out = collective("all_to_all", in_tensor, group)
    if isinstance(out_tensor, Tensor):
        out_tensor.set_value(out.value)
    return _Task(out)


def broadcast(tensor, src=0, group=None, sync_op=True):
    g = get_group(group)
    src_local = g.get_group_rank(src) if g.ranks and src in g.ranks else src
    out = collective("broadcast", tensor, group, extra=(int(src_local),))
    if isinstance(tensor, Tensor):
        tensor.set_value(out.value)
    return _Task(out)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    g = get_group(group)
    dst_local = g.get_group_rank(dst) if g.ranks and dst in g.ranks else dst
    out = collective("reduce", tensor, group, extra=(op, int(dst_local)))
    if isinstance(tensor, Tensor):
        tensor.set_value(out.value)
    return _Task(out)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    from ...ops.manipulation import concat

    if tensor_list:
        inp = concat(list(tensor_list), axis=0)
        # stacked convention: every rank slot carries the full src payload
        g = get_group(group)
        if not (g.axis_name and not isinstance(g.axis_name, tuple)
                and in_traced_context(g.axis_name)):
            inp = Tensor(jnp.broadcast_to(
                inp.value[None], (g.nranks,) + tuple(inp.shape)))
    else:
        inp = tensor
    out = collective("scatter", inp, group, extra=(int(src),))
    if isinstance(tensor, Tensor):
        tensor.set_value(out.value)
    return _Task(out)


def send(tensor, dst=0, group=None, sync_op=True):
    """P2P ≙ ppermute edge (reference send_v2/recv_v2). SPMD has no caller
    rank, so send/recv express the collective ring pattern the reference's
    pipeline uses: every rank i forwards its slot to i+1 (send) and the
    matching recv reads the shifted slot. Pipeline-parallel code uses
    ppermute directly with explicit edges (meta_parallel/pp_utils)."""
    g = get_group(group)
    n = g.nranks
    perm = tuple((i, (i + 1) % n) for i in range(n))
    out = collective("ppermute", tensor, group, extra=(perm,))
    return _Task(out)


def recv(tensor, src=0, group=None, sync_op=True):
    g = get_group(group)
    n = g.nranks
    perm = tuple((i, (i + 1) % n) for i in range(n))
    out = collective("ppermute", tensor, group, extra=(perm,))
    if isinstance(tensor, Tensor):
        tensor.set_value(out.value)
    return _Task(out)


isend = send
irecv = recv


def barrier(group=None):
    import jax

    (jax.device_put(0) + 0).block_until_ready()
    return _Task()


class stream:
    """paddle.distributed.stream.* namespace parity — on XLA the async/stream
    choice (process_group_with_stream.h:32-56 sync_op/use_calc_stream) is the
    compiler's; these re-export the same ops."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    all_to_all = staticmethod(all_to_all)
    broadcast = staticmethod(broadcast)
    scatter = staticmethod(scatter)
    reduce = staticmethod(reduce)
    send = staticmethod(send)
    recv = staticmethod(recv)
