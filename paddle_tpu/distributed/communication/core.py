"""Collective communication core (TPU-native ProcessGroup replacement).

Reference three-tier backend (SURVEY.md §5.8: ProcessGroupNCCL /
CommContext kernels / legacy c_* ops) collapses into ONE mechanism here:
every collective is an XLA collective over a named mesh axis.

Two calling contexts, same ops:
- **traced** (inside ``shard_map``/``pjit`` over the mesh): the tensor is the
  per-device shard; ops lower to ``lax.psum/all_gather/...`` directly —
  these ride ICI on hardware.
- **eager** ("stacked-ranks" convention): the tensor's LEADING axis indexes
  the group's ranks (rank i's local tensor = t[i]), mirroring how the
  reference's per-process tensors line up side by side. The op runs a jitted
  ``shard_map`` over the group axis, so data placed on the mesh keeps its
  sharding and the collective still executes as an XLA collective.

Why stacked-ranks: single-controller SPMD has no per-process local tensor;
the stacked form is bit-identical to the reference's N local tensors and is
exactly the global-array view of a mesh-sharded batch.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ...core.tensor import Tensor
from ..topology import Group, get_mesh

__all__ = ["ReduceOp", "in_traced_context", "collective", "get_group",
           "new_group", "get_global_group"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


_REDUCERS = {
    ReduceOp.SUM: lax.psum,
    ReduceOp.MAX: lax.pmax,
    ReduceOp.MIN: lax.pmin,
}


def _preduce(x, op, axis):
    if op in _REDUCERS:
        return _REDUCERS[op](x, axis)
    if op == ReduceOp.AVG:
        return lax.pmean(x, axis)
    if op == ReduceOp.PROD:
        return jnp.exp(lax.psum(jnp.log(jnp.abs(x) + 1e-30), axis)) * _sign_prod(x, axis)
    raise ValueError(f"unsupported reduce op {op}")


def _sign_prod(x, axis):
    neg = lax.psum((x < 0).astype(jnp.int32), axis)
    return jnp.where(neg % 2 == 0, 1.0, -1.0).astype(x.dtype)


def in_traced_context(axis_name: str) -> bool:
    """True when called under shard_map/pmap with axis bound."""
    try:
        lax.axis_index(axis_name)
        return True
    except NameError:
        return False
    except Exception:
        return True  # bound but used outside primitive context


_DEFAULT_GROUP: Optional[Group] = None


def get_global_group() -> Group:
    """The world group: all devices flattened onto a virtual 'world' view.
    Implemented as the dp axis when that is the only >1 axis, else an axis
    tuple over every hybrid axis."""
    global _DEFAULT_GROUP
    if _DEFAULT_GROUP is None:
        mesh = get_mesh()
        sizes = dict(mesh.shape)
        live = [a for a, s in sizes.items() if s > 1]
        axis = live[0] if len(live) == 1 else tuple(mesh.axis_names)
        _DEFAULT_GROUP = Group(axis, mesh,
                               ranks=list(range(int(np.prod(list(sizes.values()))))))
    return _DEFAULT_GROUP


def _reset_default_group():
    global _DEFAULT_GROUP
    _DEFAULT_GROUP = None


def get_group(group) -> Group:
    if group is None:
        return get_global_group()
    return group


def new_group(ranks=None, backend=None, timeout=None) -> Group:
    """collective.py:178 parity. In mesh terms a rank-list subgroup over the
    flattened device order; collectives over it use a gather-compute-scatter
    fallback (sub-axis groups beyond whole axes are rare on TPU — prefer
    whole-axis groups)."""
    mesh = get_mesh()
    return Group(None, mesh, ranks=ranks)


# ---------------------------------------------------------------------------
# The collective engine
# ---------------------------------------------------------------------------


def _flat_world_mesh(mesh: Mesh) -> Mesh:
    devs = mesh.devices.reshape(-1)
    return Mesh(devs, ("world",))


def _axis_for(group: Group):
    if group.axis_name is not None:
        return group.axis_name
    return None


@functools.lru_cache(maxsize=256)
def _build_stacked(mesh, axis, kernel_name, extra):
    """Compile a stacked-ranks collective: input leading dim = group size."""
    kernel = _KERNELS[kernel_name]

    def per_shard(x):
        # x block: [1, ...] — drop the rank dim for the kernel, re-add after
        y = kernel(x[0], axis, extra)
        return y[None] if y is not None else x

    f = shard_map(per_shard, mesh=mesh, in_specs=P(axis),
                  out_specs=P(axis), check_vma=False)
    return jax.jit(f)


# kernels: (local_value, axis, extra) -> local_result
def _k_all_reduce(x, axis, extra):
    return _preduce(x, extra[0], axis)


def _axis_size(axis):
    """lax.axis_size is missing on jax 0.4.x; psum of 1 is the portable
    spelling of a named-axis size inside a collective context."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


def _k_all_gather_stack(x, axis, extra):
    return lax.all_gather(x, axis, axis=0)  # [world, ...]


def _k_all_gather_concat(x, axis, extra):
    return lax.all_gather(x, axis, axis=0, tiled=True)  # concat on dim0


def _k_reduce_scatter(x, axis, extra):
    op = extra[0]
    if op == ReduceOp.SUM:
        return lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
    full = _preduce(x, op, axis)
    n = _axis_size(axis)
    i = lax.axis_index(axis)
    chunk = x.shape[0] // n
    return lax.dynamic_slice_in_dim(full, i * chunk, chunk, 0)


def _k_all_to_all(x, axis, extra):
    n = _axis_size(axis)
    xs = x.reshape((n, x.shape[0] // n) + x.shape[1:])
    return lax.all_to_all(xs, axis, split_axis=0, concat_axis=0,
                          tiled=False).reshape(x.shape)


def _k_broadcast(x, axis, extra):
    src = extra[0]
    full = lax.all_gather(x, axis, axis=0)
    return full[src]


def _k_reduce(x, axis, extra):
    op, dst = extra
    red = _preduce(x, op, axis)
    i = lax.axis_index(axis)
    return jnp.where(i == dst, red, x)


def _k_scatter(x, axis, extra):
    # x: each rank holds the FULL [world*chunk, ...] on src; take own chunk
    src = extra[0]
    full = lax.all_gather(x, axis, axis=0)[src]
    n = _axis_size(axis)
    i = lax.axis_index(axis)
    chunk = full.shape[0] // n
    return lax.dynamic_slice_in_dim(full, i * chunk, chunk, 0)


def _k_ppermute(x, axis, extra):
    perm = extra[0]
    return lax.ppermute(x, axis, perm=perm)


_KERNELS = {
    "all_reduce": _k_all_reduce,
    "all_gather_stack": _k_all_gather_stack,
    "all_gather_concat": _k_all_gather_concat,
    "reduce_scatter": _k_reduce_scatter,
    "all_to_all": _k_all_to_all,
    "broadcast": _k_broadcast,
    "reduce": _k_reduce,
    "scatter": _k_scatter,
    "ppermute": _k_ppermute,
}


def collective(kernel_name: str, tensor, group=None, extra=()):
    """Run a collective in either context. Eager input follows the
    stacked-ranks convention (leading dim == group size)."""
    g = get_group(group)
    axis = _axis_for(g)
    value = tensor.value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    extra = tuple(extra)

    if axis is not None and not isinstance(axis, tuple) and in_traced_context(axis):
        out = _KERNELS[kernel_name](value, axis, extra)
        return Tensor(out)

    mesh = g.mesh
    if axis is None or isinstance(axis, tuple):
        # world / rank-list group: flatten devices to one axis
        mesh = _flat_world_mesh(mesh)
        axis = "world"
        n = g.nranks
    else:
        n = int(mesh.shape[axis])
    if value.shape[0] != n:
        raise ValueError(
            f"stacked-ranks collective expects leading dim == group size "
            f"({n}), got shape {value.shape}. Inside shard_map the per-shard "
            f"form is used automatically.")
    fn = _build_stacked(mesh, axis, kernel_name, extra)
    return Tensor(fn(value))
