"""Distributed pass framework (reference: distributed/passes/pass_base.py
— new_pass:?, PassManager, PassContext — plus the auto_parallel_* pass set
applied by the static Engine).

TPU-native: a "pass" transforms the recorded-op Program
(static/program.py) — the same IR the executor jits — instead of a
ProgramDesc. The passes that survive on TPU are the ones that change the
COMPUTATION (precision casts, rematerialization, quantization); the ones
that existed to inject collectives (sharding/pipeline/data-parallel
passes) are carried by sharding annotations + GSPMD and are intentionally
absent here (DESIGN.md role-collapse notes).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

__all__ = ["PassContext", "PassBase", "PassManager", "new_pass",
           "register_pass"]

_REGISTRY: Dict[str, type] = {}


class PassContext:
    """Shared state across a pass pipeline (reference PassContext)."""

    def __init__(self):
        self._attrs: Dict[str, Any] = {}

    def set_attr(self, key, value):
        self._attrs[key] = value

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)


class PassBase:
    name: str = "base"

    def __init__(self, attrs: Optional[Dict[str, Any]] = None):
        self._attrs = dict(attrs or {})

    def set_attr(self, key, value):
        self._attrs[key] = value
        return self

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)

    # reference contract: check then apply
    def check_before_apply(self, main_program, startup_program) -> bool:
        return True

    def apply(self, main_programs, startup_programs=None,
              context: Optional[PassContext] = None):
        """Apply to one program or a list; returns the transformed
        program(s) (recorded Programs are immutably cloned)."""
        single = not isinstance(main_programs, (list, tuple))
        progs = [main_programs] if single else list(main_programs)
        outs = []
        for p in progs:
            if not self.check_before_apply(p, None):
                raise ValueError(f"pass {self.name} preconditions failed")
            outs.append(self._apply_single(p, context or PassContext()))
        return outs[0] if single else outs

    def _apply_single(self, program, context):
        raise NotImplementedError


def register_pass(name: str):
    def deco(cls):
        cls.name = name
        _REGISTRY[name] = cls
        return cls

    return deco


def new_pass(name: str, pass_attrs: Optional[Dict[str, Any]] = None
             ) -> PassBase:
    """reference new_pass(name, attrs) — construct a registered pass."""
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown pass {name!r}; registered: {sorted(_REGISTRY)}")
    p = _REGISTRY[name](pass_attrs)
    p.name = name   # a class may register under aliases (amp/fp16)
    return p


class PassManager:
    """reference PassManager: ordered pipeline over programs."""

    def __init__(self, passes: List[PassBase]):
        self._passes = list(passes)
        self.context = PassContext()

    @property
    def names(self):
        return [p.name for p in self._passes]

    def apply(self, main_programs, startup_programs=None):
        single = not isinstance(main_programs, (list, tuple))
        progs = [main_programs] if single else list(main_programs)
        for p in self._passes:
            progs = [p.apply(pr, None, self.context) for pr in progs]
        return progs[0] if single else progs


# ---------------------------------------------------------------------------
# TPU-native pass set
# ---------------------------------------------------------------------------

_MATMUL_OPS = ("matmul", "linear", "mul", "conv2d")


def _clone_with_nodes(program, nodes):
    out = program.clone()
    out.nodes = nodes
    return out


@register_pass("auto_parallel_fp16")
@register_pass("auto_parallel_amp")
class AMPPass(PassBase):
    """Cast matmul-class compute to bf16 (reference auto_parallel_amp /
    fp16 passes insert cast ops around fp16-safe ops). attrs:
    ``dtype`` ("bfloat16"), ``custom_white_list`` (extra op names)."""

    def _apply_single(self, program, context):
        from ...static.program import StaticNode

        # alias-aware default: the fp16 registration means FLOAT16 unless
        # the caller says otherwise (bf16 would silently change mantissa)
        default_dt = ("float16" if self.name == "auto_parallel_fp16"
                      else "bfloat16")
        dt = jnp.bfloat16 if self.get_attr("dtype", default_dt) in (
            "bfloat16", "bf16") else jnp.float16
        white = set(_MATMUL_OPS) | {
            str(n).lower() for n in self.get_attr("custom_white_list", ())}
        # a black-listed op must NOT be cast even if it is in the default
        # matmul set — the user marked it numerically unsafe
        white -= {str(n).lower()
                  for n in self.get_attr("custom_black_list", ())}
        new_nodes = []
        for node in program.nodes:
            if (node.name or "").lower() not in white:
                new_nodes.append(node)
                continue

            def cast_fn(*flat, _fn=node.fn, _dt=dt):
                lo = [x.astype(_dt) if hasattr(x, "astype")
                      and jnp.issubdtype(jnp.result_type(x), jnp.floating)
                      else x for x in flat]
                out = _fn(*lo)
                return jax.tree.map(
                    lambda o: o.astype(jnp.float32)
                    if hasattr(o, "astype") and jnp.issubdtype(
                        jnp.result_type(o), jnp.floating) else o, out)

            new_nodes.append(StaticNode(
                fn=cast_fn, in_ids=node.in_ids, const_args=node.const_args,
                out_ids=node.out_ids, name=node.name))
        out = _clone_with_nodes(program, new_nodes)
        context.set_attr("amp_applied", True)
        return out


@register_pass("auto_parallel_recompute")
class RecomputePass(PassBase):
    """Rematerialize matched ops in the backward (reference
    auto_parallel_recompute segments the program; here jax.checkpoint on
    the node function IS the segment marker — XLA recomputes it in the
    grad pass instead of saving residuals). attrs: ``ops`` (names to
    wrap; default matmul-class)."""

    def _apply_single(self, program, context):
        from ...static.program import StaticNode

        targets = {str(n).lower() for n in self.get_attr("ops",
                                                         _MATMUL_OPS)}
        new_nodes = []
        n = 0
        for node in program.nodes:
            if (node.name or "").lower() not in targets:
                new_nodes.append(node)
                continue
            new_nodes.append(StaticNode(
                fn=jax.checkpoint(node.fn), in_ids=node.in_ids,
                const_args=node.const_args, out_ids=node.out_ids,
                name=node.name))
            n += 1
        out = _clone_with_nodes(program, new_nodes)
        context.set_attr("recomputed_ops", n)
        return out


@register_pass("auto_parallel_quantization")
class QuantizationPass(PassBase):
    """Delegates to the program-level QAT transform
    (static/quantization.QuantizationTransformPass). attrs:
    ``weight_bits``/``activation_bits``."""

    def _apply_single(self, program, context):
        from ...static.quantization import QuantizationTransformPass

        return QuantizationTransformPass(
            weight_bits=self.get_attr("weight_bits", 8),
            activation_bits=self.get_attr("activation_bits", 8),
        ).apply(program)
