"""ZeRO stage-1: optimizer-state sharding over the ``sharding`` mesh axis.

Reference: DygraphShardingOptimizer
(meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:29): greedy
size-ordered partition of params across sharding ranks; each rank runs the
inner optimizer on its own shard and broadcasts updated params post-step.

TPU-native redesign: there is no per-rank partition list. Optimizer states
are logical global arrays *placed sharded*: each accumulator created for a
parameter is device_put with a PartitionSpec that shards its largest
divisible axis over ``sharding`` (on top of whatever mp axes the param
already uses). GSPMD then keeps the optimizer update an all-local op over
state shards — exactly ZeRO-1's memory saving — and the "post-step
broadcast" is the all-gather XLA inserts wherever the updated param is
consumed replicated.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from .._spmd import get_pspec
from ..topology import get_mesh

__all__ = ["shard_optimizer_states", "state_pspec"]


def state_pspec(param, mesh=None) -> P:
    """PartitionSpec for an optimizer state of `param`: the param's own spec
    with the sharding axis added on the first free, divisible dim."""
    mesh = mesh or get_mesh()
    deg = int(mesh.shape.get("sharding", 1))
    base = get_pspec(param) or P()
    shape = tuple(param.shape) if hasattr(param, "shape") else ()
    spec = list(base) + [None] * (len(shape) - len(base))

    def _has_sharding(entry):
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        return "sharding" in names

    already = any(e is not None and _has_sharding(e) for e in spec)
    if deg > 1 and not already:
        for i, dim in enumerate(shape):
            if spec[i] is None and dim % deg == 0:
                spec[i] = "sharding"
                break
    return P(*spec)


def shard_optimizer_states(optimizer, mesh=None):
    """Install sharded-state placement on an Optimizer: every accumulator it
    creates from now on (and any already created) is placed with
    ``state_pspec``. Idempotent."""
    mesh = mesh or get_mesh()
    if getattr(optimizer, "_sharded_states", False):
        return optimizer
    optimizer._sharded_states = True
    params_by_key = {}
    if optimizer._parameter_list:
        for p in optimizer._parameter_list:
            params_by_key[p.name if p.name else f"param_{id(p)}"] = p

    def _place(pkey, value):
        p = params_by_key.get(pkey)
        if p is None:
            return value
        if np.ndim(value) == 0 or not hasattr(value, "shape") or value.shape == ():
            return value
        if value.shape != tuple(int(s) for s in p.shape):
            return value  # beta-power style scalars / odd states
        sh = NamedSharding(mesh, state_pspec(p, mesh))
        try:
            return jax.device_put(value, sh)
        except Exception:
            return value

    # place existing accumulators
    for acc_name, d in optimizer._accumulators.items():
        for pkey in list(d.keys()):
            d[pkey] = _place(pkey, d[pkey])

    # wrap _acc so future accumulators are placed at creation
    orig_acc = optimizer._acc

    def _acc(name, p, init=None):
        d = optimizer._accumulators.setdefault(name, {})
        k = optimizer._key(p)
        fresh = k not in d
        v = orig_acc(name, p, init)
        if fresh:
            d[k] = _place(k, v)
            return d[k]
        return v

    optimizer._acc = _acc
    return optimizer
