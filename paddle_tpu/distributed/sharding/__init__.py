"""paddle.distributed.sharding parity (distributed/sharding/group_sharded.py).

ZeRO-style sharding on the ``sharding`` mesh axis. TPU-native: sharding a
state means annotating it with a PartitionSpec over the sharding axis and
letting GSPMD place/partition it — reduce-scatter of grads and all-gather of
params fall out of the sharding propagation (scaling-book ZeRO recipe).
"""
from .group_sharded import group_sharded_parallel, save_group_sharded_model
from .sharded_optimizer import shard_optimizer_states

__all__ = ["group_sharded_parallel", "save_group_sharded_model",
           "shard_optimizer_states"]
