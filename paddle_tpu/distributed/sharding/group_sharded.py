"""group_sharded_parallel — public ZeRO API.

Reference: distributed/sharding/group_sharded.py (wraps model+optimizer in
GroupSharded{OptimizerStage2,Stage2,Stage3} by ``level``).

TPU-native: all three levels are sharding-annotation policies over the
``sharding`` mesh axis rather than runtime hook machinery:
- os  (stage 1): optimizer states sharded            → shard_optimizer_states
- os_g (stage 2): + gradients sharded (reduce-scatter falls out of GSPMD
  when the grad consumer — the sharded state update — is sharded)
- p_g_os (stage 3): + parameters sharded between uses (param pspecs gain a
  sharding-axis dim; XLA all-gathers on use and frees after)
"""
from __future__ import annotations

from ..topology import get_mesh
from .sharded_optimizer import shard_optimizer_states

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2**23, segment_size=2**20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """reference group_sharded.py:32 parity (same levels: os | os_g | p_g_os)."""
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"level must be os | os_g | p_g_os, got {level}")
    mesh = get_mesh()
    if level == "os":
        shard_optimizer_states(optimizer, mesh)
    elif level == "os_g":
        from ..fleet.meta_parallel.sharding import (
            GroupShardedOptimizerStage2, GroupShardedStage2)

        optimizer = GroupShardedOptimizerStage2(
            params=model.parameters(), optim=optimizer, group=group,
            offload=offload)
        model = GroupShardedStage2(model, sharding_optimizer=optimizer,
                                   group=group, sync_buffers=sync_buffers,
                                   buffer_max_size=buffer_max_size,
                                   dp_group=dp_group)
    else:  # p_g_os
        from ..fleet.meta_parallel.sharding import GroupShardedStage3

        model = GroupShardedStage3(model, optimizer=optimizer, group=group,
                                   sync_buffers=sync_buffers,
                                   segment_size=segment_size, offload=offload,
                                   sync_comm=sync_comm, dp_group=dp_group,
                                   exclude_layer=exclude_layer)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """reference group_sharded.py save helper: state is logically global
    (GSPMD), so plain save round-trips without gathering."""
    import os

    from ...framework import io as fio

    os.makedirs(output, exist_ok=True)
    fio.save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        fio.save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
