"""paddle.distributed parity (python/paddle/distributed/__init__.py).

TPU-native distributed stack: one jax.sharding.Mesh carries the hybrid
topology (dp/pp/sharding/mp/sp/ep); collectives are XLA collectives over
mesh axes (see communication/core.py for the execution contract).
"""
from .communication import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    all_to_all,
    all_to_all_single,
    barrier,
    broadcast,
    irecv,
    isend,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    stream,
)
from .communication.core import get_group, new_group  # noqa: F401
from .env import get_rank, get_world_size  # noqa: F401
from .parallel import DataParallel, ParallelEnv, init_parallel_env  # noqa: F401
from .topology import (  # noqa: F401
    CommunicateTopology,
    Group,
    HybridCommunicateGroup,
    build_mesh,
    get_mesh,
    set_mesh,
)


def is_initialized() -> bool:
    from .parallel import _initialized

    return _initialized[0]


def get_backend() -> str:
    return "xla"


_EXTRAS = ("alltoall", "alltoall_single", "gather", "split", "wait",
           "broadcast_object_list", "scatter_object_list",
           "destroy_process_group", "is_available", "ParallelMode",
           "gloo_init_parallel_env", "gloo_barrier", "gloo_release",
           "InMemoryDataset", "QueueDataset", "CountFilterEntry",
           "ProbabilityEntry", "ShowClickEntry")


def __getattr__(name):
    import importlib

    if name in ("fleet", "sharding", "checkpoint", "utils", "meta_parallel",
                "auto_parallel", "launch", "sequence_parallel", "rpc",
                "auto_tuner", "io"):
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name == "spawn":
        from .spawn import spawn as _spawn

        globals()[name] = _spawn
        return _spawn
    if name in _EXTRAS:
        mod = importlib.import_module("._extras", __name__)
        for n in _EXTRAS:
            globals()[n] = getattr(mod, n)
        return globals()[name]
    if name in ("ring_attention", "ulysses_attention", "split_sequence",
                "gather_sequence"):
        from . import sequence_parallel as sp_mod

        return getattr(sp_mod, name)
    if name == "TCPStore":
        from ..native import TCPStore

        return TCPStore
    if name == "passes":
        from . import passes as passes_mod

        return passes_mod
    raise AttributeError(f"module 'paddle_tpu.distributed' has no attribute {name!r}")


def __dir__():
    lazy = {"fleet", "sharding", "checkpoint", "utils", "meta_parallel",
            "auto_parallel", "launch", "sequence_parallel", "rpc",
            "auto_tuner", "io", "spawn", "TCPStore", "passes"}
    return sorted(set(globals()) | lazy | set(_EXTRAS))
