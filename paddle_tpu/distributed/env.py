"""Distributed environment state (minimal core; full topology in topology.py).

Holds the process-level parallel context: rank/world size and — TPU-native —
the active named-mesh axis used when a layer wants cross-replica collectives
while being traced under shard_map (e.g. SyncBatchNorm's pmean over 'dp').
"""
from __future__ import annotations

import contextlib
import os
import threading
from typing import Optional


class _Env(threading.local):
    def __init__(self):
        self.sync_axis: Optional[str] = None


_env = _Env()


def current_sync_axis() -> Optional[str]:
    return _env.sync_axis


@contextlib.contextmanager
def sync_axis_scope(axis: Optional[str]):
    prev = _env.sync_axis
    _env.sync_axis = axis
    try:
        yield
    finally:
        _env.sync_axis = prev


def get_rank() -> int:
    return int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", 0)))


def get_world_size() -> int:
    return int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", 1)))
