"""Device-mesh topology (fleet/base/topology.py:58,144 parity, TPU-native).

The reference builds a 4-D process topology (dp/pp/sharding/mp) out of
per-process NCCL groups (CommunicateTopology + HybridCommunicateGroup).
TPU-native redesign: ONE ``jax.sharding.Mesh`` with named axes carries the
whole hybrid topology; a "communication group" is a mesh axis (sub-mesh), and
collectives are XLA collectives over that axis riding ICI. Axes extend the
reference's set with ``sp`` (sequence/context parallel) and ``ep`` (expert
parallel) as first-class dims (SURVEY.md §5.7/§5.8).

Single-controller SPMD note: there is no per-process "rank" — rank-shaped
APIs (get_model_parallel_rank etc.) return the host process's coordinate
(multi-host) or 0 (single host), while the per-device coordinate is
``lax.axis_index(axis)`` inside traced code.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["CommunicateTopology", "HybridCommunicateGroup", "get_mesh",
           "set_mesh", "build_mesh", "axis_size", "Group"]

# canonical hybrid axis order (reference default order: data/pipe/sharding/model,
# fleet/fleet.py:393-416; sp+ep appended as capability extensions)
HYBRID_AXES = ("dp", "pp", "sharding", "mp", "sp", "ep")

_GLOBAL_MESH: Optional[Mesh] = None
_GROUPS: Dict[int, "Group"] = {}
_NEXT_GROUP_ID = [0]


class Group:
    """A communication group ≙ one mesh axis (or an explicit rank list for
    API-parity subgroups). reference: collective.py Group."""

    def __init__(self, axis_name: Optional[str], mesh: Mesh, ranks=None,
                 gid: Optional[int] = None):
        self.axis_name = axis_name
        self.mesh = mesh
        if gid is None:
            gid = _NEXT_GROUP_ID[0]
            _NEXT_GROUP_ID[0] += 1
        self.id = gid
        if ranks is None and axis_name is not None:
            ranks = list(range(mesh.shape[axis_name]))
        self.ranks = ranks or []
        _GROUPS[self.id] = self

    @property
    def nranks(self) -> int:
        if self.axis_name is not None:
            return int(self.mesh.shape[self.axis_name])
        return len(self.ranks)

    @property
    def world_size(self) -> int:
        return self.nranks

    @property
    def rank(self) -> int:
        """Group-local coordinate of THIS process (reference Group.rank).

        Single-host single-controller: 0 (device coord = lax.axis_index
        inside traced code). Multi-host: the axis coordinate of the first
        mesh device owned by this process — e.g. on a 2-host dp=2 mesh,
        host 1 sees dp rank 1."""
        if self.axis_name is not None:
            return process_axis_coord(self.mesh, self.axis_name)
        if self.ranks:
            from .env import get_rank

            # -1 for non-members (reference Group.rank contract): leader
            # checks like `if group.rank == 0` must not fire on outsiders
            return self.get_group_rank(get_rank())
        return 0

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self):
        return self

    def __repr__(self):
        return f"Group(id={self.id}, axis={self.axis_name}, nranks={self.nranks})"


def process_axis_coord(mesh: Mesh, axis_name: str) -> int:
    """Axis coordinate of the current process's first owned device in the
    mesh (0 when this process owns none / single-process)."""
    try:
        pid = jax.process_index()
    except Exception:
        return 0
    if pid == 0 and jax.process_count() == 1:
        return 0
    axis = list(mesh.axis_names).index(axis_name)
    for coord, dev in np.ndenumerate(mesh.devices):
        if getattr(dev, "process_index", 0) == pid:
            return int(coord[axis])
    return 0


def build_mesh(dp: int = 1, pp: int = 1, sharding: int = 1, mp: int = 1,
               sp: int = 1, ep: int = 1, devices=None,
               order: Sequence[str] = HYBRID_AXES) -> Mesh:
    """Build the global hybrid mesh. Degrees must multiply to #devices
    (a trailing dp axis absorbs the remainder if left as default 1)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    degrees = {"dp": dp, "pp": pp, "sharding": sharding, "mp": mp,
               "sp": sp, "ep": ep}
    prod = int(np.prod([max(1, d) for d in degrees.values()]))
    if prod != n:
        if n % prod == 0 and dp == 1:
            degrees["dp"] = n // prod
        else:
            raise ValueError(
                f"hybrid degrees {degrees} multiply to {prod}, but there are "
                f"{n} devices")
    shape = [degrees[a] for a in order]
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axis_names=tuple(order))


def set_mesh(mesh: Mesh):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_mesh() -> Mesh:
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        _GLOBAL_MESH = build_mesh()
    return _GLOBAL_MESH


def axis_size(axis: str) -> int:
    m = get_mesh()
    return int(m.shape[axis]) if axis in m.shape else 1


class CommunicateTopology:
    """fleet/base/topology.py:58 parity — named-dim coordinate math."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = tuple  # type alias
        self._world_size = int(np.prod(self._dims))
        ranges = [range(d) for d in self._dims]
        import itertools

        self._coord2rank = {c: i for i, c in enumerate(itertools.product(*ranges))}
        self._rank2coord = {v: k for k, v in self._coord2rank.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[n] for n in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on axis_name == index."""
        axis = self._parallel_names.index(axis_name)
        return [r for c, r in self._coord2rank.items() if c[axis] == index]

    def get_comm_list(self, axis_name):
        """Rank lists of each group along axis_name (varying that axis only)."""
        axis = self._parallel_names.index(axis_name)
        groups = {}
        for coord, rank in self._coord2rank.items():
            key = coord[:axis] + coord[axis + 1:]
            groups.setdefault(key, []).append(rank)
        return [sorted(v) for _, v in sorted(groups.items())]

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = list(self.get_coord(global_rank))
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return self._coord2rank[tuple(coord)]


class HybridCommunicateGroup:
    """fleet/base/topology.py:144 parity over the global Mesh.

    Mesh-axis mapping: data→dp, pipe→pp, sharding→sharding, model→mp
    (+ sp, ep). check group (dp×pp) has no single mesh axis; it is exposed as
    an axis tuple for multi-axis collectives.
    """

    def __init__(self, topology: Optional[CommunicateTopology] = None,
                 mesh: Optional[Mesh] = None):
        self._mesh = mesh if mesh is not None else get_mesh()
        ms = dict(self._mesh.shape)
        self._dp_degree = ms.get("dp", 1)
        self._pp_degree = ms.get("pp", 1)
        self._sharding_degree = ms.get("sharding", 1)
        self._mp_degree = ms.get("mp", 1)
        self._sp_degree = ms.get("sp", 1)
        self._ep_degree = ms.get("ep", 1)
        self._topo = topology or CommunicateTopology(
            ("data", "pipe", "sharding", "model"),
            (self._dp_degree, self._pp_degree, self._sharding_degree,
             self._mp_degree))
        self.global_rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self._dp_group = Group("dp", self._mesh)
        self._pp_group = Group("pp", self._mesh)
        self._sharding_group = Group("sharding", self._mesh)
        self._mp_group = Group("mp", self._mesh)
        self._sp_group = Group("sp", self._mesh)
        self._ep_group = Group("ep", self._mesh)

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    # nranks
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sequence_parallel_world_size(self):
        return self._sp_degree

    def get_expert_parallel_world_size(self):
        return self._ep_degree

    # ranks (host view — see module docstring)
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    # groups
    def get_data_parallel_group(self):
        return self._dp_group

    def get_model_parallel_group(self):
        return self._mp_group

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sequence_parallel_group(self):
        return self._sp_group

    def get_expert_parallel_group(self):
        return self._ep_group

    def get_check_parallel_group(self, sharding=False):
        return Group(None, self._mesh, ranks=list(range(
            self._dp_degree * self._pp_degree)))

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0

    # pipeline helpers
    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    def get_p2p_groups(self):
        return None

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank, pipe=stage_id)

    def topology(self):
        return self._topo
