"""paddle.dataset parity (reference: python/paddle/dataset/ — the legacy
reader-style dataset loaders, superseded in 2.x by paddle.vision.datasets
and paddle.text).

This build keeps the module shape: `common` utilities are real; the
per-dataset loaders delegate to the maintained vision/text dataset classes
(download gated — zero-egress build, pass local paths).
"""
from . import common

__all__ = ["common", "uci_housing", "imdb", "imikolov", "movielens"]


class _DelegatingLoader:
    """reader-style wrapper over a Dataset class: train()/test() return
    zero-arg reader callables (the paddle.dataset contract)."""

    def __init__(self, cls, name):
        self._cls = cls
        self.__name__ = name

    def _reader(self, mode, **kwargs):
        def reader():
            ds = self._cls(mode=mode, **kwargs)
            for i in range(len(ds)):
                yield ds[i]

        return reader

    def train(self, **kwargs):
        return self._reader("train", **kwargs)

    def test(self, **kwargs):
        return self._reader("test", **kwargs)


def __getattr__(name):
    if name == "uci_housing":
        from ..text.datasets import UCIHousing

        return _DelegatingLoader(UCIHousing, name)
    if name == "imdb":
        from ..text.datasets import Imdb

        return _DelegatingLoader(Imdb, name)
    if name == "imikolov":
        from ..text.datasets import Imikolov

        return _DelegatingLoader(Imikolov, name)
    if name == "movielens":
        from ..text.datasets import Movielens

        return _DelegatingLoader(Movielens, name)
    raise AttributeError(f"module 'paddle_tpu.dataset' has no attribute "
                         f"{name!r}")
