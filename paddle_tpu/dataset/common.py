"""paddle.dataset.common parity (reference: python/paddle/dataset/
common.py — md5file, DATA_HOME, download (gated), split/cluster readers).
"""
from __future__ import annotations

import hashlib
import os
import pickle
from typing import Callable

__all__ = ["DATA_HOME", "md5file", "download", "split",
           "cluster_files_reader"]

DATA_HOME = os.path.expanduser(
    os.environ.get("DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def md5file(fname: str) -> str:
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url: str, module_name: str, md5sum: str,
             save_name: str = None) -> str:
    """Zero-egress build: resolves to an existing local file or raises
    with instructions (reference common.py:download fetches over HTTP)."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name or url.split("/")[-1])
    if os.path.exists(filename) and (not md5sum
                                     or md5file(filename) == md5sum):
        return filename
    raise RuntimeError(
        f"no local copy of {url}: this build has no network egress. "
        f"Download it on a connected machine and place it at {filename}.")


def split(reader: Callable, line_count: int, suffix: str = "%05d.pickle",
          dumper=pickle.dump):
    """Split a reader's samples into pickled chunk files (reference
    common.py:split)."""
    indx_f = 0
    lines = []
    for i, d in enumerate(reader()):
        lines.append(d)
        if i >= (indx_f + 1) * line_count - 1:
            with open(suffix % indx_f, "wb") as f:
                dumper(lines, f)
            lines = []
            indx_f += 1
    if lines:
        with open(suffix % indx_f, "wb") as f:
            dumper(lines, f)


def cluster_files_reader(files_pattern: str, trainer_count: int,
                         trainer_id: int, loader=pickle.load):
    """Round-robin chunk-file reader for one trainer (reference
    common.py:cluster_files_reader)."""
    import glob

    def reader():
        file_list = sorted(glob.glob(files_pattern))
        my_files = [f for i, f in enumerate(file_list)
                    if i % trainer_count == trainer_id]
        for fn in my_files:
            with open(fn, "rb") as f:
                for line in loader(f):
                    yield line

    return reader
