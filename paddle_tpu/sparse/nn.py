"""paddle.sparse.nn — sparse layers + functional (reference:
python/paddle/sparse/nn/{layer,functional}: __all__ ReLU/ReLU6/LeakyReLU/
Softmax/BatchNorm/SyncBatchNorm/Conv2D/Conv3D/SubmConv2D/SubmConv3D/
MaxPool3D; functional adds conv*/subm_conv*/max_pool3d/attention).

TPU-native design notes:

- **Activations** run directly on the stored values — zero-preserving fns
  (relu, relu6, leaky_relu) keep the sparsity structure untouched, no
  densify.
- **Softmax** is the reference's sparse semantics: normalize over the
  PRESENT entries of each row (missing entries are -inf, not 0). Computed
  through a dense mask — on TPU a masked dense softmax beats gather-based
  sparsity for moderate sizes (same reasoning as
  nn/functional/sparse_attention).
- **BatchNorm/SyncBatchNorm** normalize the channel dim of the values
  (reference sparse BN operates on [nnz, C] values). Under SPMD, jax
  arrays are global, so "sync" stats are the default — SyncBatchNorm is
  the same computation (class kept for API parity).
- **SubmConv** runs a TRUE gather-GEMM submanifold convolution over the
  active sites (``_subm_gather_gemm``: sort + searchsorted neighbor maps,
  one batched einsum on the MXU, memory O(K·nnz·C) — a 128³ point cloud
  at 0.1% density never sees the 2M-voxel dense volume). This is the
  reference's rulebook + gather/scatter GEMM
  (sparse/gpu/conv_kernel.cu subm path) built jit-static.
- **Strided Conv / MaxPool** lower through XLA's dense conv on the
  densified tensor and re-sparsify — a documented small-grid fallback:
  their OUTPUT site set is data-dependent (stride changes the active
  set), which cannot be a static-shape jit program; workloads needing
  big strided sparse convs should restructure around SubmConv + pooling.
  SubmConv keeps the INPUT's active sites (submanifold contract).
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "SyncBatchNorm", "Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D",
           "MaxPool3D", "functional"]


def _raw(x):
    return x._value if isinstance(x, Tensor) else x


def _sp(v):
    from . import SparseTensor

    return SparseTensor(v)


def _map_values(x, fn):
    """Apply fn to stored values, preserving structure (COO or CSR)."""
    v = _raw(x)
    if isinstance(v, jsparse.BCOO):
        return _sp(jsparse.BCOO((fn(v.data), v.indices), shape=v.shape))
    if isinstance(v, jsparse.BCSR):
        return _sp(jsparse.BCSR((fn(v.data), v.indices, v.indptr),
                                shape=v.shape))
    return Tensor(fn(v))


def _dense_of(x):
    v = _raw(x)
    if isinstance(v, (jsparse.BCOO, jsparse.BCSR)):
        return jnp.asarray(v.todense()), True
    return jnp.asarray(v), False


def _densify_guard(x, what: str, stacklevel: int = 3):
    """The strided conv / pooling fallbacks materialize the FULL dense
    volume (module docstring: their output site set is data-dependent, so
    they cannot be static-shape sparse programs). Keeping that contract
    only in the docstring let big grids densify silently (VERDICT r4
    Weak #4) — surface it at call time: warn above a size threshold,
    ``PADDLE_TPU_SPARSE_DENSIFY=error`` refuses, ``=silent`` opts out.
    Threshold in elements: ``PADDLE_TPU_SPARSE_DENSIFY_WARN_ELEMS``
    (default 2^24 ≈ 16.7M, a 256³ fp32 volume = 64 MiB)."""
    import os
    import warnings

    v = _raw(x)
    if not isinstance(v, (jsparse.BCOO, jsparse.BCSR)):
        return  # already dense: nothing extra is materialized here
    elems = int(np.prod(v.shape))
    thresh = int(os.environ.get("PADDLE_TPU_SPARSE_DENSIFY_WARN_ELEMS",
                                1 << 24))
    if elems < thresh:
        return
    mode = os.environ.get("PADDLE_TPU_SPARSE_DENSIFY", "warn")
    msg = (f"sparse {what} lowers through a DENSE {tuple(v.shape)} volume "
           f"({elems:,} elements) — the strided sparse paths are "
           "documented small-grid fallbacks (output site sets are data-"
           "dependent; see paddle_tpu/sparse/nn.py). Restructure around "
           "SubmConv2D/3D for large grids, set "
           "PADDLE_TPU_SPARSE_DENSIFY=error to refuse, =silent to "
           "acknowledge, or raise PADDLE_TPU_SPARSE_DENSIFY_WARN_ELEMS.")
    if mode == "error":
        raise ValueError(msg)
    if mode != "silent":
        warnings.warn(msg, RuntimeWarning, stacklevel=stacklevel)


# -- functional -------------------------------------------------------------


def relu(x, name=None):
    return _map_values(x, lambda d: jnp.maximum(d, 0))


def relu6(x, name=None):
    return _map_values(x, lambda d: jnp.clip(d, 0, 6))


def leaky_relu(x, negative_slope: float = 0.01, name=None):
    return _map_values(x, lambda d: jnp.where(d >= 0, d,
                                              negative_slope * d))


def softmax(x, axis: int = -1, name=None):
    """Softmax over PRESENT entries only (reference sparse softmax:
    missing entries behave as -inf, and stay missing in the output)."""
    v = _raw(x)
    dense, was_sparse = _dense_of(x)
    if not was_sparse:
        return Tensor(jax.nn.softmax(dense, axis=axis))
    mask = jnp.asarray(
        (jsparse.BCOO((jnp.ones_like(v.data, jnp.float32), v.indices),
                      shape=v.shape).todense() > 0)
        if isinstance(v, jsparse.BCOO) else
        (jsparse.BCSR((jnp.ones_like(v.data, jnp.float32), v.indices,
                       v.indptr), shape=v.shape).todense() > 0))
    s = jnp.where(mask, dense.astype(jnp.float32), -jnp.inf)
    p = jax.nn.softmax(s, axis=axis)
    p = jnp.where(mask, p, 0.0).astype(dense.dtype)
    if isinstance(v, jsparse.BCSR):   # format-preserving (CSR-first op)
        return _sp(jsparse.BCSR.fromdense(p, nse=v.nse))
    return _sp(jsparse.BCOO.fromdense(p, nse=v.nse))


def _conv_dense(x_dense, weight, bias, stride, padding, dilation, groups,
                nd: int):
    """NDHWC/NHWC dense conv via lax (weight [*k, Cin/groups, Cout])."""
    w = _raw(weight)
    strides = (stride,) * nd if isinstance(stride, int) else tuple(stride)
    dil = (dilation,) * nd if isinstance(dilation, int) else tuple(dilation)
    if isinstance(padding, int):
        pad = [(padding, padding)] * nd
    elif isinstance(padding, str):
        pad = padding
    else:
        pad = [(p, p) if isinstance(p, int) else tuple(p) for p in padding]
    dims = ("NHWC", "HWIO", "NHWC") if nd == 2 else \
        ("NDHWC", "DHWIO", "NDHWC")
    out = jax.lax.conv_general_dilated(
        x_dense, w, window_strides=strides, padding=pad,
        rhs_dilation=dil, dimension_numbers=dims,
        feature_group_count=groups)
    if bias is not None:
        out = out + _raw(bias)
    return out


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    _densify_guard(x, "conv2d")
    dense, _ = _dense_of(x)
    out = _conv_dense(dense, weight, bias, stride, padding, dilation,
                      groups, nd=2)
    return _sp(jsparse.BCOO.fromdense(out, n_batch=0, n_dense=1))


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    _densify_guard(x, "conv3d")
    dense, _ = _dense_of(x)
    out = _conv_dense(dense, weight, bias, stride, padding, dilation,
                      groups, nd=3)
    return _sp(jsparse.BCOO.fromdense(out, n_batch=0, n_dense=1))


def _subm(x, out_dense):
    """Submanifold: keep only the INPUT's active spatial sites."""
    dense_in, _ = _dense_of(x)
    # active site = any input channel nonzero at that spatial location
    active = jnp.any(dense_in != 0, axis=-1, keepdims=True)
    out = jnp.where(active, out_dense, 0)
    return _sp(jsparse.BCOO.fromdense(out, n_batch=0, n_dense=1))


def _subm_gather_gemm(v, weight, bias, dilation, nd: int):
    """True submanifold convolution: gather -> batched GEMM over active
    sites only (reference: paddle/phi/kernels/sparse/gpu/conv_kernel.cu
    subm path — rulebook build + gather/scatter GEMM). Never materializes
    the dense volume: memory is O(K·nnz·C), so a 128^3 grid at 0.1%
    density costs what its ~2k points cost, not what 2M voxels would.

    TPU shape: every piece is static-capacity so it jits — nnz comes from
    the BCOO's nse, the kernel offset set K is static, and the neighbor
    map is built with sort + searchsorted over LINEARIZED coordinates
    (log-time lookup, no grid-sized hash table):

      out[i] = bias + sum_delta  values[nbr(i, delta)] @ W[delta]

    where nbr is resolved per offset by binary search; misses (neighbor
    inactive or out of bounds) contribute zero. The GEMM is one
    ``einsum('kni,kio->no')`` — K·nnz rows batched onto the MXU.

    Semantics note: a site is active iff its COORDINATE is stored
    (structural sparsity, like the reference's rulebook built from
    indices) — an explicitly stored all-zero value vector still counts
    as an active site. Indices must be unique (canonical COO).
    """
    import itertools

    w = _raw(weight)
    coords = v.indices.astype(jnp.int32)          # (nnz, 1 + nd)
    vals = v.data                                 # (nnz, Cin)
    nnz = vals.shape[0]
    spatial = tuple(int(s) for s in v.shape[1:1 + nd])
    # keys are int32 (x64 is disabled): batch * prod(spatial) must fit,
    # or sort/searchsorted silently wrap and return WRONG neighbors
    key_space = int(v.shape[0]) * int(np.prod(spatial))
    if key_space >= 2 ** 31:
        raise ValueError(
            f"submanifold conv coordinate space {v.shape[:1 + nd]} needs "
            f"{key_space} linearized keys, which overflows int32; split "
            "the batch into chunks so batch * prod(spatial) < 2**31")
    cin, cout = w.shape[-2], w.shape[-1]
    ks = tuple(int(k) for k in w.shape[:nd])
    dil = (dilation,) * nd if isinstance(dilation, int) else tuple(dilation)

    def linearize(batch, sp_coords):
        key = batch
        for d in range(nd):
            key = key * spatial[d] + sp_coords[:, d]
        return key

    key = linearize(coords[:, 0], coords[:, 1:])
    order = jnp.argsort(key)
    skey = key[order]

    center = [(k - 1) // 2 for k in ks]           # lax SAME alignment
    offsets = list(itertools.product(*[range(k) for k in ks]))
    sp_dims = jnp.asarray(spatial, jnp.int32)
    gathered = []
    for off in offsets:
        delta = jnp.asarray(
            [(off[d] - center[d]) * dil[d] for d in range(nd)], jnp.int32)
        nb = coords[:, 1:] + delta
        inb = jnp.all((nb >= 0) & (nb < sp_dims), axis=1)
        nkey = linearize(coords[:, 0], nb)
        pos = jnp.clip(jnp.searchsorted(skey, nkey), 0, nnz - 1)
        hit = (skey[pos] == nkey) & inb
        src = order[pos]
        gathered.append(jnp.where(hit[:, None], vals[src], 0))
    stacked = jnp.stack(gathered)                 # (K, nnz, Cin)
    wk = w.reshape(-1, cin, cout)                 # (K, Cin, Cout)
    out = jnp.einsum("kni,kio->no", stacked, wk)
    if bias is not None:
        out = out + _raw(bias)
    return _sp(jsparse.BCOO((out.astype(vals.dtype), v.indices),
                            shape=v.shape[:1 + nd] + (cout,)))


def _check_subm_stride(stride):
    ok = stride in (1, None) or (not isinstance(stride, int)
                                 and all(int(s) == 1 for s in stride))
    if not ok:
        raise ValueError(
            "submanifold convolution keeps output sites == input sites, "
            "which requires stride 1 (got stride={!r}); use Conv2D/Conv3D "
            "for strided sparse convolution".format(stride))


def _subm_conv(x, weight, bias, stride, padding, dilation, groups, nd):
    _check_subm_stride(stride)
    v = _raw(x)
    # gather-GEMM over active sites (the real sparse path); dense lowering
    # remains ONLY for the cases it still covers: non-sparse inputs,
    # grouped convs, and explicit non-SAME padding (all small-grid /
    # API-parity fallbacks — they materialize the dense volume)
    if (isinstance(v, jsparse.BCOO) and v.n_dense == 1 and groups == 1
            and v.indices.shape[-1] == nd + 1 and padding in (0, "SAME")):
        return _subm_gather_gemm(v, weight, bias, dilation, nd)
    _densify_guard(x, "subm_conv (grouped/non-SAME-padding fallback)",
                   stacklevel=4)  # user -> subm_conv3d -> _subm_conv
    dense, _ = _dense_of(x)
    out = _conv_dense(dense, weight, bias, 1, "SAME" if padding in (
        0, "SAME") else padding, dilation, groups, nd=nd)
    return _subm(x, out)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    return _subm_conv(x, weight, bias, stride, padding, dilation, groups, 2)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    return _subm_conv(x, weight, bias, stride, padding, dilation, groups, 3)


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    _densify_guard(x, "max_pool3d")
    dense, _ = _dense_of(x)
    ks = (kernel_size,) * 3 if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else (
        (stride,) * 3 if isinstance(stride, int) else tuple(stride))
    if isinstance(padding, int):
        pad = [(0, 0)] + [(padding, padding)] * 3 + [(0, 0)]
    else:  # per-spatial-dim paddle style: wrap with batch/channel pairs
        pad = [(0, 0)] + [
            (p, p) if isinstance(p, int) else tuple(p) for p in padding
        ] + [(0, 0)]
    out = jax.lax.reduce_window(
        dense, -jnp.inf, jax.lax.max,
        window_dimensions=(1,) + ks + (1,),
        window_strides=(1,) + st + (1,),
        padding=pad)
    out = jnp.where(jnp.isfinite(out), out, 0)
    return _sp(jsparse.BCOO.fromdense(out, n_batch=0, n_dense=1))


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """CSR-pattern attention (reference sparse/nn/functional/attention.py):
    the sparse_mask CSR structure selects which (q, k) pairs participate.
    Delegates to the dense-masked sparse_attention lowering."""
    from ..nn.functional.flash_attention import sparse_attention

    v = _raw(sparse_mask)
    crows = jnp.broadcast_to(
        v.indptr, query.shape[:2] + v.indptr.shape).reshape(
            query.shape[0], query.shape[1], -1) \
        if isinstance(v, jsparse.BCSR) else None
    if crows is None:
        raise ValueError("sparse_mask must be a CSR SparseTensor")
    cols = jnp.broadcast_to(
        v.indices, query.shape[:2] + v.indices.shape).reshape(
            query.shape[0], query.shape[1], -1)
    return sparse_attention(query, key, value, Tensor(crows), Tensor(cols),
                            key_padding_mask=key_padding_mask,
                            attn_mask=attn_mask)


# -- layers -----------------------------------------------------------------


class ReLU:
    def __call__(self, x):
        return relu(x)


class ReLU6:
    def __call__(self, x):
        return relu6(x)


class LeakyReLU:
    def __init__(self, negative_slope: float = 0.01):
        self._slope = negative_slope

    def __call__(self, x):
        return leaky_relu(x, self._slope)


class Softmax:
    def __init__(self, axis: int = -1):
        self._axis = axis

    def __call__(self, x):
        return softmax(x, self._axis)


class BatchNorm:
    """Sparse BatchNorm over the channel (last) dim of the stored values
    (reference sparse/nn/layer/norm.py BatchNorm: statistics over active
    elements only — zeros from missing sites do NOT dilute the mean)."""

    def __init__(self, num_features: int, momentum: float = 0.9,
                 epsilon: float = 1e-5, data_format="NDHWC", name=None):
        self.num_features = num_features
        self._momentum = momentum
        self._eps = epsilon
        self.weight = Tensor(jnp.ones((num_features,), jnp.float32))
        self.bias = Tensor(jnp.zeros((num_features,), jnp.float32))
        self._mean = jnp.zeros((num_features,), jnp.float32)
        self._var = jnp.ones((num_features,), jnp.float32)
        self.training = True

    def train(self):
        self.training = True
        return self

    def eval(self):
        self.training = False
        return self

    def __call__(self, x):
        v = _raw(x)
        if not isinstance(v, jsparse.BCOO) or v.data.ndim < 2:
            raise ValueError(
                "sparse BatchNorm expects a COO tensor with [nnz, C] "
                "values (build it with sparse_coo_tensor over channel-"
                "vector values)")
        vals = v.data.astype(jnp.float32)             # (nnz, C)
        if self.training:
            mean = jnp.mean(vals, axis=0)
            var = jnp.var(vals, axis=0)
            m = self._momentum
            self._mean = m * self._mean + (1 - m) * mean
            self._var = m * self._var + (1 - m) * var
        else:
            mean, var = self._mean, self._var
        out = (vals - mean) * jax.lax.rsqrt(var + self._eps)
        out = out * _raw(self.weight) + _raw(self.bias)
        return _sp(jsparse.BCOO((out.astype(v.data.dtype), v.indices),
                                shape=v.shape))


class SyncBatchNorm(BatchNorm):
    """Cross-replica sparse BN. Under SPMD the value arrays are GLOBAL, so
    the statistics in :class:`BatchNorm` already span every replica — the
    reference needs an explicit allreduce (sync_batch_norm_kernel) because
    its tensors are per-rank. Kept as a distinct class for API parity and
    for convert_sync_batchnorm-style swaps."""


class _ConvBase:
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False, nd=3,
                 bias_attr=None, data_format=None):
        from ..core.random import default_generator

        ks = (kernel_size,) * nd if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        fan_in = in_channels * int(np.prod(ks))
        bound = 1.0 / np.sqrt(fan_in)
        # framework RNG: paddle.seed() must make these reproducible, like
        # every dense layer's initializer
        self.weight = Tensor(jax.random.uniform(
            default_generator.next_key(),
            ks + (in_channels // groups, out_channels),
            jnp.float32, -bound, bound))
        self.bias = None if bias_attr is False else Tensor(
            jnp.zeros((out_channels,), jnp.float32))
        self._args = (stride, padding, dilation, groups)
        self._subm = subm
        self._nd = nd

    def __call__(self, x):
        stride, padding, dilation, groups = self._args
        fn = {(2, False): conv2d, (3, False): conv3d,
              (2, True): subm_conv2d, (3, True): subm_conv3d}[
            (self._nd, self._subm)]
        return fn(x, self.weight, self.bias, stride, padding, dilation,
                  groups)


class Conv2D(_ConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(in_channels, out_channels, kernel_size, nd=2,
                         subm=False, **kw)


class Conv3D(_ConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(in_channels, out_channels, kernel_size, nd=3,
                         subm=False, **kw)


class SubmConv2D(_ConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(in_channels, out_channels, kernel_size, nd=2,
                         subm=True, **kw)


class SubmConv3D(_ConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, **kw):
        super().__init__(in_channels, out_channels, kernel_size, nd=3,
                         subm=True, **kw)


class MaxPool3D:
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        self._args = (kernel_size, stride, padding)

    def __call__(self, x):
        return max_pool3d(x, *self._args)


class _Functional:
    conv2d = staticmethod(conv2d)
    conv3d = staticmethod(conv3d)
    subm_conv2d = staticmethod(subm_conv2d)
    subm_conv3d = staticmethod(subm_conv3d)
    max_pool3d = staticmethod(max_pool3d)
    relu = staticmethod(relu)
    relu6 = staticmethod(relu6)
    leaky_relu = staticmethod(leaky_relu)
    softmax = staticmethod(softmax)
    attention = staticmethod(attention)
    __all__ = ["conv2d", "conv3d", "subm_conv2d", "subm_conv3d",
               "max_pool3d", "relu", "relu6", "leaky_relu", "softmax",
               "attention"]


functional = _Functional()


functional_relu = relu   # round-2 facade back-compat
