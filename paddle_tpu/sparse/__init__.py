"""paddle.sparse parity (reference: python/paddle/sparse/ — COO/CSR tensor
API over phi/kernels/sparse). TPU-native: jax.experimental.sparse BCOO/BCSR
is the storage; XLA lowers sparse ops to gather/scatter-matmul on TPU."""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseTensor",
           "is_same_shape", "add", "matmul", "masked_matmul", "relu",
           "nn"]


class SparseTensor(Tensor):
    """Tensor holding a BCOO/BCSR value (reference SparseCooTensor /
    SparseCsrTensor, phi/core/sparse_coo_tensor.h)."""

    __slots__ = ()

    @property
    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return isinstance(self._value, jsparse.BCOO)

    def is_sparse_csr(self):
        return isinstance(self._value, jsparse.BCSR)

    def to_dense(self) -> Tensor:
        return Tensor(self._value.todense())

    def indices(self) -> Tensor:
        return Tensor(self._value.indices.T)  # paddle layout [ndim, nnz]

    def values(self) -> Tensor:
        return Tensor(self._value.data)

    def crows(self) -> Tensor:
        return Tensor(self._value.indptr)

    def cols(self) -> Tensor:
        return Tensor(self._value.indices)

    @property
    def nnz(self) -> int:
        return int(self._value.nse)

    def numpy(self):
        return np.asarray(self._value.todense())


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """reference paddle.sparse.sparse_coo_tensor: indices [ndim, nnz]."""
    idx = np.asarray(indices._value if isinstance(indices, Tensor)
                     else indices)
    val = jnp.asarray(values._value if isinstance(values, Tensor) else values,
                      dtype=dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    coo = jsparse.BCOO((val, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseTensor(coo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    val = jnp.asarray(values._value if isinstance(values, Tensor) else values,
                      dtype=dtype)
    indptr = jnp.asarray(crows._value if isinstance(crows, Tensor) else crows)
    idx = jnp.asarray(cols._value if isinstance(cols, Tensor) else cols)
    csr = jsparse.BCSR((val, idx, indptr), shape=tuple(shape))
    return SparseTensor(csr, stop_gradient=stop_gradient)


def is_same_shape(x, y) -> bool:
    return tuple(x.shape) == tuple(y.shape)


def _raw(x):
    return x._value if isinstance(x, Tensor) else x


def add(x, y, name=None):
    """Sparse+sparse add. Support pattern is the UNION of operands;
    computed via densify (fine for the API-parity sizes; a fused
    union-merge kernel is the optimization path for large nnz)."""
    xv, yv = _raw(x), _raw(y)
    if isinstance(xv, (jsparse.BCOO, jsparse.BCSR)) and isinstance(
            yv, (jsparse.BCOO, jsparse.BCSR)):
        return SparseTensor(jsparse.BCOO.fromdense(
            xv.todense() + yv.todense()))
    return Tensor(_dense(xv) + _dense(yv))


def _dense(v):
    return v.todense() if isinstance(v, (jsparse.BCOO, jsparse.BCSR)) else v


def matmul(x, y, name=None):
    """sparse @ dense (reference paddle.sparse.matmul)."""
    xv, yv = _raw(x), _raw(y)
    if isinstance(xv, jsparse.BCSR):
        xv = jsparse.BCOO.from_bcsr(xv)
    if isinstance(xv, jsparse.BCOO):
        return Tensor(xv @ yv)
    return Tensor(xv @ _dense(yv))


def masked_matmul(x, y, mask, name=None):
    """dense @ dense with sparse output pattern (reference
    paddle.sparse.masked_matmul; SDDMM). The output carries EXACTLY the
    mask's index set — values are gathered at the mask's coordinates, so
    output.values() aligns 1:1 with the mask (even where the product is 0)."""
    out = _dense(_raw(x)) @ _dense(_raw(y))
    mv = _raw(mask)
    if isinstance(mv, jsparse.BCSR):
        mv = jsparse.BCOO.from_bcsr(mv)
    if not isinstance(mv, jsparse.BCOO):
        mv = jsparse.BCOO.fromdense(jnp.asarray(mv) != 0)
    rows = mv.indices[:, 0]
    cols = mv.indices[:, 1]
    vals = out[rows, cols]
    return SparseTensor(jsparse.BCOO((vals, mv.indices), shape=out.shape))


def relu(x, name=None):
    v = _raw(x)
    if isinstance(v, (jsparse.BCOO, jsparse.BCSR)):
        out = jsparse.BCOO(
            (jnp.maximum(v.data if hasattr(v, "data") else v.values, 0),
             v.indices), shape=v.shape) if isinstance(v, jsparse.BCOO) else \
            jsparse.BCSR((jnp.maximum(v.data, 0), v.indices, v.indptr),
                         shape=v.shape)
        return SparseTensor(out)
    return Tensor(jnp.maximum(v, 0))




# -- elementwise unary over the stored values (zero-preserving fns keep the
#    sparsity pattern; reference phi/kernels/sparse/unary_kernel.cc) -------


def _unary_on_values(fn, opname):
    def op(x, name=None):
        v = _raw(x)
        if isinstance(v, jsparse.BCOO):
            return SparseTensor(jsparse.BCOO((fn(v.data), v.indices),
                                             shape=v.shape))
        if isinstance(v, jsparse.BCSR):
            return SparseTensor(jsparse.BCSR((fn(v.data), v.indices,
                                              v.indptr), shape=v.shape))
        return Tensor(fn(jnp.asarray(_dense(v))))

    op.__name__ = opname
    return op


abs = _unary_on_values(jnp.abs, "abs")                     # noqa: A001
sin = _unary_on_values(jnp.sin, "sin")
sinh = _unary_on_values(jnp.sinh, "sinh")
asin = _unary_on_values(jnp.arcsin, "asin")
asinh = _unary_on_values(jnp.arcsinh, "asinh")
tan = _unary_on_values(jnp.tan, "tan")
tanh = _unary_on_values(jnp.tanh, "tanh")
atan = _unary_on_values(jnp.arctan, "atan")
atanh = _unary_on_values(jnp.arctanh, "atanh")
sqrt = _unary_on_values(jnp.sqrt, "sqrt")
square = _unary_on_values(jnp.square, "square")
log1p = _unary_on_values(jnp.log1p, "log1p")
expm1 = _unary_on_values(jnp.expm1, "expm1")
neg = _unary_on_values(jnp.negative, "neg")
deg2rad = _unary_on_values(jnp.deg2rad, "deg2rad")
rad2deg = _unary_on_values(jnp.rad2deg, "rad2deg")
isnan = _unary_on_values(jnp.isnan, "isnan")


def pow(x, factor, name=None):                              # noqa: A001
    return _unary_on_values(lambda v: jnp.power(v, factor), "pow")(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    """Cast indices and/or values (reference sparse/unary cast)."""
    v = _raw(x)
    if isinstance(v, jsparse.BCSR):
        v = jsparse.BCOO.from_bcsr(v)
    if isinstance(v, jsparse.BCOO):
        data = v.data.astype(value_dtype) if value_dtype else v.data
        idx = v.indices.astype(index_dtype) if index_dtype else v.indices
        return SparseTensor(jsparse.BCOO((data, idx), shape=v.shape))
    return Tensor(jnp.asarray(v).astype(value_dtype or v.dtype))


def coalesce(x, name=None):
    """Merge duplicate coordinates (reference sparse coalesce)."""
    v = _raw(x)
    if isinstance(v, jsparse.BCOO):
        return SparseTensor(v.sum_duplicates())
    return x


# -- binary (pattern union via densify, same policy as add) ----------------


def _binary(fn, opname):
    def op(x, y, name=None):
        xv, yv = _raw(x), _raw(y)
        both_sparse = isinstance(xv, (jsparse.BCOO, jsparse.BCSR)) and \
            isinstance(yv, (jsparse.BCOO, jsparse.BCSR))
        out = fn(jnp.asarray(_dense(xv)), jnp.asarray(_dense(yv)))
        if both_sparse:
            return SparseTensor(jsparse.BCOO.fromdense(out))
        return Tensor(out)

    op.__name__ = opname
    return op


subtract = _binary(jnp.subtract, "subtract")
multiply = _binary(jnp.multiply, "multiply")
divide = _binary(lambda a, b: jnp.where(b != 0, a / jnp.where(b == 0, 1, b),
                                        jnp.nan * a), "divide")


def mv(x, vec, name=None):
    """sparse matrix @ dense vector (reference sparse/matmul mv)."""
    return matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) (reference sparse addmm)."""
    prod = matmul(x, y)
    pv = jnp.asarray(_dense(_raw(prod)))
    iv = jnp.asarray(_dense(_raw(input)))
    out = beta * iv + alpha * pv
    if isinstance(_raw(input), (jsparse.BCOO, jsparse.BCSR)):
        return SparseTensor(jsparse.BCOO.fromdense(out))
    return Tensor(out)


# -- shape ops -------------------------------------------------------------


def reshape(x, shape, name=None):
    v = _raw(x)
    if isinstance(v, jsparse.BCOO):
        return SparseTensor(jsparse.BCOO.fromdense(
            v.todense().reshape(shape)))
    return Tensor(jnp.reshape(jnp.asarray(_dense(v)), shape))


def transpose(x, perm, name=None):
    v = _raw(x)
    if isinstance(v, jsparse.BCOO):
        from jax.experimental.sparse import bcoo_transpose

        return SparseTensor(bcoo_transpose(v, permutation=tuple(perm)))
    return Tensor(jnp.transpose(jnp.asarray(_dense(v)), perm))


def slice(x, axes, starts, ends, name=None):                # noqa: A001
    import builtins

    v = _raw(x)
    dense = jnp.asarray(_dense(v))
    idx = [builtins.slice(None)] * dense.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[int(ax)] = builtins.slice(int(s), int(e))
    out = dense[tuple(idx)]
    if isinstance(v, (jsparse.BCOO, jsparse.BCSR)):
        return SparseTensor(jsparse.BCOO.fromdense(out))
    return Tensor(out)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    v = _raw(x)
    dense = jnp.asarray(_dense(v))
    out = jnp.sum(dense, axis=axis, keepdims=keepdim, dtype=dtype)
    if isinstance(v, (jsparse.BCOO, jsparse.BCSR)) and out.ndim > 0:
        return SparseTensor(jsparse.BCOO.fromdense(out))
    return Tensor(out)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA over a (densified) sparse matrix (reference
    sparse pca_lowrank)."""
    from ..ops.linalg import pca_lowrank as _dense_pca

    return _dense_pca(Tensor(jnp.asarray(_dense(_raw(x)))), q=q,
                      center=center, niter=niter)


__all__ += ["abs", "sin", "sinh", "asin", "asinh", "tan", "tanh", "atan",
            "atanh", "sqrt", "square", "log1p", "expm1", "neg", "deg2rad",
            "rad2deg", "isnan", "pow", "cast", "coalesce", "subtract",
            "multiply", "divide", "mv", "addmm", "reshape", "transpose",
            "slice", "sum", "pca_lowrank"]


# nn subpackage last: its layers reference SparseTensor defined above
from . import nn  # noqa: E402,F401
