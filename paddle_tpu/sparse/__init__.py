"""paddle.sparse parity (reference: python/paddle/sparse/ — COO/CSR tensor
API over phi/kernels/sparse). TPU-native: jax.experimental.sparse BCOO/BCSR
is the storage; XLA lowers sparse ops to gather/scatter-matmul on TPU."""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseTensor",
           "is_same_shape", "add", "matmul", "masked_matmul", "relu",
           "nn"]


class SparseTensor(Tensor):
    """Tensor holding a BCOO/BCSR value (reference SparseCooTensor /
    SparseCsrTensor, phi/core/sparse_coo_tensor.h)."""

    __slots__ = ()

    @property
    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return isinstance(self._value, jsparse.BCOO)

    def is_sparse_csr(self):
        return isinstance(self._value, jsparse.BCSR)

    def to_dense(self) -> Tensor:
        return Tensor(self._value.todense())

    def indices(self) -> Tensor:
        return Tensor(self._value.indices.T)  # paddle layout [ndim, nnz]

    def values(self) -> Tensor:
        return Tensor(self._value.data)

    def crows(self) -> Tensor:
        return Tensor(self._value.indptr)

    def cols(self) -> Tensor:
        return Tensor(self._value.indices)

    @property
    def nnz(self) -> int:
        return int(self._value.nse)

    def numpy(self):
        return np.asarray(self._value.todense())


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """reference paddle.sparse.sparse_coo_tensor: indices [ndim, nnz]."""
    idx = np.asarray(indices._value if isinstance(indices, Tensor)
                     else indices)
    val = jnp.asarray(values._value if isinstance(values, Tensor) else values,
                      dtype=dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    coo = jsparse.BCOO((val, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseTensor(coo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    val = jnp.asarray(values._value if isinstance(values, Tensor) else values,
                      dtype=dtype)
    indptr = jnp.asarray(crows._value if isinstance(crows, Tensor) else crows)
    idx = jnp.asarray(cols._value if isinstance(cols, Tensor) else cols)
    csr = jsparse.BCSR((val, idx, indptr), shape=tuple(shape))
    return SparseTensor(csr, stop_gradient=stop_gradient)


def is_same_shape(x, y) -> bool:
    return tuple(x.shape) == tuple(y.shape)


def _raw(x):
    return x._value if isinstance(x, Tensor) else x


def add(x, y, name=None):
    """Sparse+sparse add. Support pattern is the UNION of operands;
    computed via densify (fine for the API-parity sizes; a fused
    union-merge kernel is the optimization path for large nnz)."""
    xv, yv = _raw(x), _raw(y)
    if isinstance(xv, (jsparse.BCOO, jsparse.BCSR)) and isinstance(
            yv, (jsparse.BCOO, jsparse.BCSR)):
        return SparseTensor(jsparse.BCOO.fromdense(
            xv.todense() + yv.todense()))
    return Tensor(_dense(xv) + _dense(yv))


def _dense(v):
    return v.todense() if isinstance(v, (jsparse.BCOO, jsparse.BCSR)) else v


def matmul(x, y, name=None):
    """sparse @ dense (reference paddle.sparse.matmul)."""
    xv, yv = _raw(x), _raw(y)
    if isinstance(xv, jsparse.BCSR):
        xv = jsparse.BCOO.from_bcsr(xv)
    if isinstance(xv, jsparse.BCOO):
        return Tensor(xv @ yv)
    return Tensor(xv @ _dense(yv))


def masked_matmul(x, y, mask, name=None):
    """dense @ dense with sparse output pattern (reference
    paddle.sparse.masked_matmul; SDDMM). The output carries EXACTLY the
    mask's index set — values are gathered at the mask's coordinates, so
    output.values() aligns 1:1 with the mask (even where the product is 0)."""
    out = _dense(_raw(x)) @ _dense(_raw(y))
    mv = _raw(mask)
    if isinstance(mv, jsparse.BCSR):
        mv = jsparse.BCOO.from_bcsr(mv)
    if not isinstance(mv, jsparse.BCOO):
        mv = jsparse.BCOO.fromdense(jnp.asarray(mv) != 0)
    rows = mv.indices[:, 0]
    cols = mv.indices[:, 1]
    vals = out[rows, cols]
    return SparseTensor(jsparse.BCOO((vals, mv.indices), shape=out.shape))


def relu(x, name=None):
    v = _raw(x)
    if isinstance(v, (jsparse.BCOO, jsparse.BCSR)):
        out = jsparse.BCOO(
            (jnp.maximum(v.data if hasattr(v, "data") else v.values, 0),
             v.indices), shape=v.shape) if isinstance(v, jsparse.BCOO) else \
            jsparse.BCSR((jnp.maximum(v.data, 0), v.indices, v.indptr),
                         shape=v.shape)
        return SparseTensor(out)
    return Tensor(jnp.maximum(v, 0))


class _SparseNN:
    """paddle.sparse.nn facade (ReLU / functional softmax on values)."""

    class ReLU:
        def __call__(self, x):
            return relu(x)

    @staticmethod
    def functional_relu(x):
        return relu(x)


nn = _SparseNN()
