"""paddle_tpu.tracing — per-request lifecycle tracing + fault flight recorder.

(The natural name ``paddle_tpu.trace`` is taken by the paddle-parity
math op ``paddle.trace(x)`` — a submodule import would shadow that
public function on the package, so the package is ``tracing``; call
sites alias it as ``trace``.)

The monitor (``paddle_tpu.monitor``) answers "how is serving doing in
AGGREGATE" with counters and histograms; the profiler answers "where
did this traced window go" with per-OP spans. THIS package answers the
two questions production serving actually debugs with:

- *"request 17's TTFT was terrible — which phase ate the time?"* —
  every serving seam (queue enqueue/dequeue/expire, admission including
  the prefill bucket choice and each chunked-prefill chunk, the
  inter-segment gap and its pressure-relief pass, decode segments with
  step counts, preempt / replay / restart / backoff, prefix-cache
  hit / copy-on-write / park / evict, speculative-verify acceptance,
  fault classification) records a structured span or instant event
  keyed by request id into one process-wide bounded ring buffer, and a
  request's ordered timeline is assembled ON DEMAND
  (``RequestHandle.timeline()``, ``Server.request_timeline(rid)``, the
  HTTP ``GET /trace?rid=`` debug endpoint) — never maintained eagerly;
- *"what was the engine doing in the seconds before it died?"* — the
  same ring IS the **flight recorder**: :func:`dump` writes the last N
  events to a file, and the serving scheduler auto-dumps on
  engine-scoped faults, ``degraded`` watchdog flips, and preemption
  storms, surfacing the dump path in ``/healthz`` and
  ``Server.fault_stats()`` so an operator (or a future multi-replica
  router) can pull the black box off a sick engine.

Cost model — the same bar as ``FLAGS_enable_monitor``: every recording
entry point checks one module-level bool first, so with tracing off the
instrumented paths pay a branch (plus one no-op context manager on the
span sites) and nothing else. Recording granularity is per
request-lifecycle edge and per decode segment — never per token and
never per op — so tracing ON stays cheap enough for production serving
(the ``serve_bench --trace-ab`` record in PERF.md quantifies it).

Event shape (dict form, what every surface returns)::

    {"phase": "admit", "rid": "server0:3", "ts_ns": ..., "dur_ns": ...,
     **attrs}                      # dur_ns == 0 marks an instant event

``rid`` is the SERVING-layer request key (``<server_label>:<handle id>``
for scheduler-driven requests — unique across concurrent servers in one
process), NOT the engine rid: engine rids change across replay/restart
while the handle id does not, which is exactly why a timeline survives
both. Batch-wide events (decode segments) carry the live handles under
``attrs["rids"]`` and are included in each of those requests'
timelines.

Export: :func:`export_chrome` / :func:`dump` write Chrome-trace /
Perfetto JSON through the profiler's shared writer
(:func:`paddle_tpu.profiler.write_chrome_trace`) — open the file in
``chrome://tracing`` or https://ui.perfetto.dev, or feed it to
``tools/monitor_report.py --trace FILE`` for a per-phase latency table.

Enable via ``FLAGS_enable_trace=1`` in the environment,
``paddle_tpu.set_flags({"FLAGS_enable_trace": True})``, or
:func:`enable` here. The ring is bounded (default 65536 events,
:func:`configure`); old events drop silently — a timeline for a
long-finished request may be partial, which is the documented price of
a black box that can stay on forever.
"""
from __future__ import annotations

import itertools
import os
import re
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "enable", "disable", "enabled", "configure", "clear",
    "event", "span", "record", "events", "timeline",
    "export_chrome", "dump", "NULL_SPAN",
    "DEFAULT_CAPACITY",
]

DEFAULT_CAPACITY = 65536

_enabled = False  # synced from FLAGS_enable_trace below
_lock = threading.Lock()
# ring entries: (ts_ns, dur_ns, rid, phase, attrs_or_None). One bounded
# deque is both the per-request event store AND the flight recorder —
# timelines are assembled on demand by scanning it, so the hot path is
# a single locked append
_ring: deque = deque(maxlen=DEFAULT_CAPACITY)
_dump_dir: Optional[str] = None     # None -> tempfile.gettempdir()
_dump_seq = itertools.count()


def enabled() -> bool:
    return _enabled


def _sync_enabled(value: bool) -> None:
    """Flag push target (framework.flags.set_flags) — flips the
    fast-path bool. No hooks to install: call sites check
    :func:`enabled` themselves at serving-seam granularity."""
    global _enabled
    _enabled = bool(value)


def enable(capacity: Optional[int] = None,
           dump_dir: Optional[str] = None) -> None:
    """Turn tracing on (equivalent to
    ``set_flags({"FLAGS_enable_trace": True})``); optionally
    :func:`configure` the ring capacity / flight-dump directory
    first."""
    if capacity is not None or dump_dir is not None:
        configure(capacity=capacity, dump_dir=dump_dir)
    from ..framework.flags import set_flags

    set_flags({"FLAGS_enable_trace": True})


def disable() -> None:
    from ..framework.flags import set_flags

    set_flags({"FLAGS_enable_trace": False})


def configure(capacity: Optional[int] = None,
              dump_dir: Optional[str] = None) -> None:
    """Set the ring capacity (events kept globally — the flight
    recorder's N; the newest tail survives a shrink) and/or the
    directory flight dumps are written to (default: the system temp
    dir)."""
    global _ring, _dump_dir
    with _lock:
        if capacity is not None:
            if capacity < 1:
                raise ValueError(
                    f"capacity must be >= 1, got {capacity}")
            if capacity != _ring.maxlen:
                # rebuild (never re-point): a shrink must DROP the
                # oldest events, keeping the newest tail that fits —
                # deque(iterable, maxlen=n) keeps the last n items
                _ring = deque(_ring, maxlen=capacity)
        if dump_dir is not None:
            _dump_dir = dump_dir


def clear() -> None:
    """Drop every buffered event (capacity and enablement unchanged)."""
    with _lock:
        _ring.clear()


# -- recording ---------------------------------------------------------------


def record(phase: str, rid=None, dur_ns: int = 0, **attrs) -> None:
    """Low-level append: one event with an explicit duration (0 = an
    instant). Call sites that already measured a wall time use this;
    everyone else uses :func:`event` / :func:`span`. No-op while
    disabled."""
    if not _enabled:
        return
    ev = (time.perf_counter_ns() - int(dur_ns), int(dur_ns), rid, phase,
          attrs or None)
    with _lock:
        _ring.append(ev)


def event(phase: str, rid=None, **attrs) -> None:
    """One instant event (``dur_ns == 0``). No-op while disabled."""
    if not _enabled:
        return
    ev = (time.perf_counter_ns(), 0, rid, phase, attrs or None)
    with _lock:
        _ring.append(ev)


class _Span:
    """Context manager recording one complete event on exit, stamped
    with its entry time (so timelines sort spans by when they BEGAN)."""

    __slots__ = ("_phase", "_rid", "_attrs", "_t0")

    def __init__(self, phase, rid, attrs):
        self._phase = phase
        self._rid = rid
        self._attrs = attrs or None
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if self._t0 is not None and _enabled:
            ev = (self._t0, time.perf_counter_ns() - self._t0,
                  self._rid, self._phase, self._attrs)
            with _lock:
                _ring.append(ev)
        return False


class _NullSpan:
    """Shared no-op span for the disabled path — entering/exiting costs
    two trivial method calls and zero allocation."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


def span(phase: str, rid=None, **attrs):
    """Span context manager::

        with trace.span("admit", rid=key, plen=plen, bucket=width):
            engine.add_request(...)

    Returns :data:`NULL_SPAN` while disabled (near-zero)."""
    if not _enabled:
        return NULL_SPAN
    return _Span(phase, rid, attrs)


# -- assembly ----------------------------------------------------------------


def _to_dict(ev) -> Dict[str, Any]:
    ts, dur, rid, phase, attrs = ev
    d: Dict[str, Any] = dict(attrs) if attrs else {}
    # the four fixed keys win over attr-name collisions
    d["phase"] = phase
    d["rid"] = rid
    d["ts_ns"] = ts
    d["dur_ns"] = dur
    return d


def events(rid=None, limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """Snapshot of the ring (insertion order — spans land at their END
    time; sort by ``ts_ns`` for begin-time order). ``rid`` filters like
    :func:`timeline`; ``limit`` keeps only the newest N."""
    with _lock:
        snap = list(_ring)
    if rid is not None:
        snap = [e for e in snap if _matches(e, rid)]
    if limit is not None:
        snap = snap[-limit:]
    return [_to_dict(e) for e in snap]


def _matches(ev, rid) -> bool:
    if ev[2] == rid:
        return True
    attrs = ev[4]
    if attrs is None:
        return False
    rids = attrs.get("rids")
    return rids is not None and rid in rids


def timeline(rid) -> List[Dict[str, Any]]:
    """One request's ordered event timeline, assembled on demand:
    every event recorded with this ``rid`` plus the batch-wide events
    (decode segments) that carried it in their ``rids`` attr, sorted
    by begin time. May be PARTIAL for old requests — the ring is
    bounded (see :func:`configure`)."""
    with _lock:
        snap = [e for e in _ring if _matches(e, rid)]
    snap.sort(key=lambda e: e[0])
    return [_to_dict(e) for e in snap]


# -- export ------------------------------------------------------------------


def _chrome_events(snap) -> List[dict]:
    out = []
    pid = os.getpid()
    for ts, dur, rid, phase, attrs in snap:
        ev = {"name": phase, "ts": ts / 1e3, "pid": pid, "tid": 0,
              "cat": "serving"}
        if dur:
            ev["ph"] = "X"
            ev["dur"] = dur / 1e3
        else:
            ev["ph"] = "i"
            ev["s"] = "g"
        args = dict(attrs) if attrs else {}
        if rid is not None:
            args["rid"] = rid
        if args:
            # Perfetto chokes on non-JSON values; everything we record
            # is already json-able (str/int/float/bool/tuples)
            ev["args"] = {k: (list(v) if isinstance(v, tuple) else v)
                          for k, v in args.items()}
        out.append(ev)
    return out


def export_chrome(path: str, rid=None,
                  other: Optional[dict] = None) -> str:
    """Write the buffered events (optionally one request's) as
    Chrome-trace/Perfetto JSON via the profiler's shared writer;
    returns ``path``."""
    from ..profiler import write_chrome_trace

    with _lock:
        snap = list(_ring)
    if rid is not None:
        snap = [e for e in snap if _matches(e, rid)]
    snap.sort(key=lambda e: e[0])
    return write_chrome_trace(path, _chrome_events(snap), other=other)


def dump(reason: str, path: Optional[str] = None) -> Optional[str]:
    """FLIGHT RECORDER dump: write the last N events (the whole ring)
    plus ``reason`` metadata to ``path`` (default
    ``<dump_dir>/paddle_tpu_flight_<pid>_<seq>_<reason>.json``) and
    return the path — or None while tracing is disabled (no black box
    was recording). The serving scheduler calls this on engine-scoped
    faults, watchdog ``degraded`` flips, and preemption storms."""
    if not _enabled:
        return None
    if path is None:
        safe = re.sub(r"[^A-Za-z0-9_.-]+", "_", reason)[:64] or "dump"
        path = os.path.join(
            _dump_dir or tempfile.gettempdir(),
            f"paddle_tpu_flight_{os.getpid()}_{next(_dump_seq)}"
            f"_{safe}.json")
    from ..monitor.provenance import env_stamp

    return export_chrome(path, other={
        "reason": reason,
        "dumped_at_unix": time.time(),
        "pid": os.getpid(),
        # chain of custody: which machine/backend/rev produced this
        # black box — without it a dump cannot be tied to a config
        "env": env_stamp(),
    })


# -- flag sync (import-time): FLAGS_enable_trace may already be set via
#    the environment; importing the package honors it ------------------------
def _init_from_flags():
    from ..framework.flags import get_flags

    _sync_enabled(get_flags("FLAGS_enable_trace")["FLAGS_enable_trace"])


_init_from_flags()
