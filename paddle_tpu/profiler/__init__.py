"""paddle.profiler parity (reference: python/paddle/profiler/profiler.py:349
Profiler, :79 scheduler states, :817 export; profiler_statistic.py).

TPU-native design: two trace sources merged under one API —
- **host spans**: a ring-buffer host event recorder (the HostTracer /
  RecordEvent analog, profiler/host_event_recorder.h) fed by an apply_op
  hook and user RecordEvent scopes;
- **device**: jax.profiler start/stop_trace (XPlane) captures XLA/TPU
  activity when a trace dir is given.
Chrome-trace export keeps the reference's span taxonomy so existing
tooling reads both.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "SortedKeys", "SummaryView", "write_chrome_trace"]


def write_chrome_trace(path: str, events: List[dict],
                       other: Optional[Dict[str, Any]] = None) -> str:
    """Shared catapult-JSON writer (reference chrometracing_logger.cc
    contract: ``ph=X`` complete events with ts/dur in µs,
    ``displayTimeUnit: ms``). ``events`` are pre-built traceEvent
    dicts; the profiler's span export and the request-lifecycle
    recorder (``paddle_tpu.tracing`` — both its chrome export and its
    flight-recorder dumps) all write through here, so every trace
    artifact this framework produces opens in chrome://tracing and
    Perfetto alike. ``other`` lands under ``otherData`` (the flight
    recorder records its dump reason there). Returns ``path``."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    doc: Dict[str, Any] = {"traceEvents": events,
                           "displayTimeUnit": "ms"}
    if other:
        doc["otherData"] = other
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


class ProfilerState(Enum):
    """reference profiler.py:79."""
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6


class _HostEventRecorder:
    """Span recorder — native C++ ring buffer (paddle_tpu.native.HostTracer,
    the host_event_recorder.h equivalent) when available, Python list
    fallback otherwise."""

    def __init__(self, capacity: int = 1_000_000):
        self.events: List[Tuple[str, int, int, int]] = []
        self.capacity = capacity
        self.active = False
        self._native = None
        try:
            from ..native import HostTracer

            self._native = HostTracer(capacity)
        except Exception:
            self._native = None

    def record(self, name: str, start_ns: int, end_ns: int):
        if self._native is not None:
            self._native.record(name, start_ns, end_ns,
                                threading.get_ident())
        elif len(self.events) < self.capacity:
            self.events.append(
                (name, start_ns, end_ns, threading.get_ident()))

    def drain(self) -> List[Tuple[str, int, int, int]]:
        if self._native is not None:
            out = [(n, s, e, t) for n, s, e, t in self._native.drain()]
        else:
            out, self.events = self.events, []
        return out


_recorder = _HostEventRecorder()


class RecordEvent:
    """User-facing span (reference platform/profiler/event_tracing.h
    RecordEvent; python API paddle.profiler.RecordEvent)."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._start = None

    def begin(self):
        self._start = time.perf_counter_ns()

    def end(self):
        if self._start is not None and _recorder.active:
            _recorder.record(self.name, self._start, time.perf_counter_ns())
        self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """reference profiler.py make_scheduler — step_num → state."""
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """reference profiler.py export_chrome_tracing — returns an on_trace_ready
    callback writing catapult JSON."""

    def handle(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_time_{int(time.time())}"
                                      ".paddle_trace.json")
        prof.export(path, format="json")

    return handle


class Profiler:
    """reference profiler.py:349."""

    def __init__(self, *, targets: Optional[Sequence[ProfilerTarget]] = None,
                 scheduler=None, on_trace_ready=None, record_shapes=False,
                 profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False):
        self.targets = list(targets or [ProfilerTarget.CPU])
        if scheduler is None:
            self.scheduler = _default_scheduler
        elif isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self.scheduler = make_scheduler(
                closed=max(lo, 0), ready=0, record=hi - lo, repeat=1)
        else:
            self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._events: List[Tuple[str, int, int, int]] = []
        self._step_marks: List[Tuple[int, int]] = []  # (step, start_ns)
        self._jax_trace_dir = None
        self._prev_op_hook = None

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self.current_state = self.scheduler(self.step_num)
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._arm()

    def _arm(self):
        _recorder.active = True
        self._install_op_hook()
        if ProfilerTarget.TPU in self.targets and not self.timer_only:
            import jax

            if jax.devices()[0].platform == "tpu":
                self._jax_trace_dir = os.path.join(
                    "/tmp", f"jax_trace_{os.getpid()}")
                jax.profiler.start_trace(self._jax_trace_dir)

    def _disarm(self):
        _recorder.active = False
        self._remove_op_hook()
        self._events.extend(_recorder.drain())
        if self._jax_trace_dir is not None:
            import jax

            jax.profiler.stop_trace()
            self._jax_trace_dir = None

    def _install_op_hook(self):
        from ..core import op_hooks

        # skip over hooks from dead profiler windows (stranded in the
        # chain because a consumer installed on top before their stop())
        prev = op_hooks.skip_dead(op_hooks.op_span_hook)
        self._prev_op_hook = prev

        def hook(name, start, end):
            if hook.armed:  # per-window flag: stranded hooks stay dead
                _recorder.record(f"op::{name}", start, end)
            if prev is not None:  # fan out (e.g. monitor's op histogram)
                prev(name, start, end)

        hook.armed = True
        hook.prev_hook = prev
        self._own_hook = hook
        op_hooks.op_span_hook = hook

    def _remove_op_hook(self):
        from ..core import op_hooks

        hook = getattr(self, "_own_hook", None)
        if hook is not None:
            hook.armed = False  # dead even if stranded in the chain
        if op_hooks.op_span_hook is hook:
            # prune: with nested windows our saved prev may itself be a
            # hook that died while we were on top of it
            op_hooks.op_span_hook = op_hooks.skip_dead(self._prev_op_hook)
        # else: someone (the monitor) installed on top AFTER we armed —
        # restoring our saved prev would silently rip them out. Leave the
        # chain; this hook forwards but never records again, and later
        # installs prune it when they capture their prev.
        self._own_hook = None
        self._prev_op_hook = None

    def stop(self):
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._disarm()
            if self.on_trace_ready:
                self.on_trace_ready(self)
        self.current_state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None):
        """Advance the schedule one training step."""
        self._step_marks.append((self.step_num, time.perf_counter_ns()))
        prev = self.current_state
        self.step_num += 1
        new = self.scheduler(self.step_num)
        rec = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if prev in rec and new not in rec:
            self._disarm()
            if self.on_trace_ready:
                self.on_trace_ready(self)
        elif prev not in rec and new in rec:
            self._arm()
        self.current_state = new

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- output -------------------------------------------------------------
    def export(self, path: str, format: str = "json"):
        """Chrome trace (catapult) export — reference chrometracing_logger.cc
    contract: ph=X complete events, ts/dur in µs."""
        events = []
        for name, start, end, tid in self._events:
            events.append({
                "name": name, "ph": "X", "cat": "op" if name.startswith(
                    "op::") else "user",
                "ts": start / 1e3, "dur": (end - start) / 1e3,
                "pid": os.getpid(), "tid": tid,
            })
        return write_chrome_trace(path, events)

    def summary(self, sorted_by: SortedKeys = SortedKeys.CPUTotal,
                op_detail: bool = True, thread_sep: bool = False,
                time_unit: str = "ms"):
        """Aggregated per-name statistics table
        (profiler_statistic.py analog). Returns the stats dict."""
        stats: Dict[str, Dict[str, float]] = {}
        for name, start, end, tid in self._events:
            d = stats.setdefault(name, {"calls": 0, "total": 0.0,
                                        "max": 0.0, "min": float("inf")})
            dur = (end - start) / 1e6  # ms
            d["calls"] += 1
            d["total"] += dur
            d["max"] = max(d["max"], dur)
            d["min"] = min(d["min"], dur)
        div = {"ms": 1.0, "us": 1e-3, "s": 1e3}[time_unit]
        rows = sorted(stats.items(), key=lambda kv: -kv[1]["total"])
        print("-" * 75)
        print(f"{'Name':<38}{'Calls':>7}{'Total(' + time_unit + ')':>12}"
              f"{'Avg':>9}{'Max':>9}")
        print("=" * 75)
        for name, d in rows:
            total = d["total"] / div
            print(f"{name[:37]:<38}{d['calls']:>7}{total:>12.3f}"
                  f"{total / d['calls']:>9.3f}{d['max'] / div:>9.3f}")
        print("-" * 75)
        return stats


def load_profiler_result(filename: str):
    with open(filename) as f:
        return json.load(f)


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready factory exporting the reference's protobuf format
    slot (reference profiler/profiler.py export_protobuf). The TPU-native
    trace artifact is the chrome-trace JSON (same data, open format) — the
    XLA/xprof .xplane.pb protobuf sits next to it when jax.profiler tracing
    is active; this export writes the chrome-trace with a .pb.json suffix
    so downstream tooling can distinguish the source."""
    import os

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        prof.export(os.path.join(dir_name, name + ".pb.json"),
                    format="json")

    return handler


__all__.append("export_protobuf")
