"""paddle.reader parity — classic reader decorators (reference:
python/paddle/reader/decorator.py).

A "reader" is a zero-arg callable returning an iterable of samples. The
decorators compose readers: caching, mapping, shuffling, chaining,
buffering, parallel mapping. xmap_readers/multiprocess_reader use threads
(the natural form here — samples flow into jit-side pipelines, the GIL is
released in numpy/IO).
"""
from __future__ import annotations

import itertools
import queue
import random
import threading
from typing import Callable

__all__ = ["cache", "map_readers", "buffered", "compose", "chain",
           "shuffle", "firstn", "xmap_readers", "multiprocess_reader"]


def cache(reader: Callable):
    """Cache the reader's full output in memory on first pass (reference
    decorator.py:45)."""
    all_data = tuple(reader())

    def cached_reader():
        yield from all_data

    return cached_reader


def map_readers(func: Callable, *readers):
    """Sample-wise map over zipped readers (reference decorator.py:84)."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader: Callable, buf_size: int):
    """Buffered shuffle (reference decorator.py:125)."""

    def shuffled_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            random.shuffle(buf)
            yield from buf

    return shuffled_reader


def chain(*readers):
    """Concatenate readers (reference decorator.py:174)."""

    def reader():
        for r in readers:
            yield from r()

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples (reference decorator.py:238)."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum((make_tuple(o) for o in outputs), ())
        else:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())

    return reader


def buffered(reader: Callable, size: int):
    """Producer-thread read-ahead buffer (reference decorator.py:296)."""

    class _End:
        pass

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)

        def produce():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e

    return buffered_reader


def firstn(reader: Callable, n: int):
    """First n samples (reference decorator.py:358)."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper: Callable, reader: Callable, process_num: int,
                 buffer_size: int, order: bool = False):
    """Parallel sample mapping with worker threads (reference
    decorator.py:403; thread-based — mappers are numpy/IO bound)."""

    class _End:
        pass

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feed():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(_End)

        def work():
            while True:
                item = in_q.get()
                if item is _End:
                    out_q.put(_End)
                    return
                i, sample = item
                out_q.put((i, mapper(sample)))

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        if order:
            pending = {}
            want = 0
            while finished < process_num:
                item = out_q.get()
                if item is _End:
                    finished += 1
                    continue
                i, mapped = item
                pending[i] = mapped
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is _End:
                    finished += 1
                    continue
                yield item[1]

    return xreader


def multiprocess_reader(readers, use_pipe: bool = True,
                        queue_size: int = 1000):
    """Merge readers with one worker thread each (reference
    decorator.py:499 uses processes; the thread form has the same
    interleaving semantics without fork hazards in a JAX process)."""

    class _End:
        pass

    def reader():
        q: queue.Queue = queue.Queue(queue_size)

        def run(r):
            try:
                for sample in r():
                    q.put(sample)
            finally:
                q.put(_End)

        for r in readers:
            threading.Thread(target=run, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            e = q.get()
            if e is _End:
                finished += 1
                continue
            yield e

    return reader
