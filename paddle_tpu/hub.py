"""paddle.hub parity (reference: python/paddle/hub.py — re-exports the
hapi.hub entrypoints)."""
from .hapi.hub import help, list, load  # noqa: F401

__all__ = ["list", "help", "load"]
