"""paddle.distribution parity (reference: python/paddle/distribution/ —
Distribution base, Normal/Uniform/Categorical/Bernoulli/Beta/Dirichlet/
Laplace/Gumbel/Multinomial/..., kl_divergence with a (p,q)-type registry).

TPU-native: samples draw explicit PRNG keys from the framework generator
(randomness is data, jit-compatible); log_prob/entropy are jnp math."""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..core.random import default_generator
from ..core.tensor import Tensor

__all__ = ["Distribution", "Normal", "Uniform", "Bernoulli", "Categorical",
           "Beta", "Dirichlet", "Laplace", "Gumbel", "Exponential",
           "Geometric", "Cauchy", "LogNormal", "Multinomial",
           "kl_divergence", "register_kl"]


def _raw(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32) if not isinstance(
        x, jnp.ndarray) else x


def _shape(sample_shape) -> Tuple[int, ...]:
    if sample_shape is None:
        return ()
    return tuple(int(s) for s in sample_shape)


class Distribution:
    """reference distribution/distribution.py Distribution."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(_raw(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _raw(loc)
        self.scale = _raw(scale)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.loc), jnp.shape(self.scale)))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    @property
    def stddev(self):
        return Tensor(jnp.broadcast_to(self.scale, self.batch_shape))

    def sample(self, shape=()):
        k = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.normal(k, s))

    rsample = sample

    def log_prob(self, value):
        v = _raw(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self.batch_shape))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _raw(low)
        self.high = _raw(high)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.low), jnp.shape(self.high)))

    @property
    def mean(self):
        return Tensor((self.low + self.high) / 2)

    @property
    def variance(self):
        return Tensor((self.high - self.low) ** 2 / 12)

    def sample(self, shape=()):
        k = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.uniform(
            k, s, minval=self.low, maxval=self.high))

    rsample = sample

    def log_prob(self, value):
        v = _raw(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _raw(probs)
        super().__init__(jnp.shape(self.probs))

    @property
    def mean(self):
        return Tensor(self.probs)

    @property
    def variance(self):
        return Tensor(self.probs * (1 - self.probs))

    def sample(self, shape=()):
        k = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.bernoulli(
            k, self.probs, s).astype(jnp.float32))

    def log_prob(self, value):
        v = _raw(value)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Categorical(Distribution):
    def __init__(self, logits=None, name=None):
        if isinstance(logits, Tensor):
            logits = logits._value
        self.logits = jnp.asarray(logits)
        super().__init__(jnp.shape(self.logits)[:-1])

    @property
    def probs_(self):
        return jax.nn.softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        k = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.categorical(k, self.logits, shape=s))

    def log_prob(self, value):
        v = _raw(value).astype(jnp.int32)
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(jnp.take_along_axis(
            logp, v[..., None], axis=-1)[..., 0])

    def probs(self, value):
        return Tensor(jnp.exp(_raw(self.log_prob(value))))

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(-jnp.sum(jnp.exp(logp) * logp, axis=-1))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _raw(alpha)
        self.beta = _raw(beta)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.alpha), jnp.shape(self.beta)))

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        t = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (t * t * (t + 1)))

    def sample(self, shape=()):
        k = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.beta(k, self.alpha, self.beta, s))

    def log_prob(self, value):
        v = _raw(value)
        from jax.scipy.special import betaln

        return Tensor((self.alpha - 1) * jnp.log(v)
                      + (self.beta - 1) * jnp.log1p(-v)
                      - betaln(self.alpha, self.beta))

    def entropy(self):
        from jax.scipy.special import betaln, digamma

        a, b = self.alpha, self.beta
        return Tensor(betaln(a, b) - (a - 1) * digamma(a)
                      - (b - 1) * digamma(b)
                      + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _raw(concentration)
        super().__init__(jnp.shape(self.concentration)[:-1],
                         jnp.shape(self.concentration)[-1:])

    @property
    def mean(self):
        return Tensor(self.concentration
                      / jnp.sum(self.concentration, -1, keepdims=True))

    def sample(self, shape=()):
        k = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.dirichlet(k, self.concentration, s))

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        v = _raw(value)
        c = self.concentration
        return Tensor(jnp.sum((c - 1) * jnp.log(v), -1)
                      + gammaln(jnp.sum(c, -1)) - jnp.sum(gammaln(c), -1))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _raw(loc)
        self.scale = _raw(scale)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.loc), jnp.shape(self.scale)))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(2 * self.scale ** 2, self.batch_shape))

    def sample(self, shape=()):
        k = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.laplace(k, s))

    rsample = sample

    def log_prob(self, value):
        v = _raw(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(jnp.broadcast_to(1 + jnp.log(2 * self.scale),
                                       self.batch_shape))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _raw(loc)
        self.scale = _raw(scale)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.loc), jnp.shape(self.scale)))

    @property
    def mean(self):
        return Tensor(self.loc + self.scale * np.euler_gamma)

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(
            (math.pi ** 2 / 6) * self.scale ** 2, self.batch_shape))

    def sample(self, shape=()):
        k = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.gumbel(k, s))

    rsample = sample

    def log_prob(self, value):
        z = (_raw(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return Tensor(jnp.broadcast_to(
            jnp.log(self.scale) + 1 + np.euler_gamma, self.batch_shape))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _raw(rate)
        super().__init__(jnp.shape(self.rate))

    @property
    def mean(self):
        return Tensor(1.0 / self.rate)

    @property
    def variance(self):
        return Tensor(1.0 / self.rate ** 2)

    def sample(self, shape=()):
        k = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.exponential(k, s) / self.rate)

    rsample = sample

    def log_prob(self, value):
        v = _raw(value)
        return Tensor(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _raw(probs)
        super().__init__(jnp.shape(self.probs))

    @property
    def mean(self):
        return Tensor(1.0 / self.probs)

    def sample(self, shape=()):
        k = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        u = jax.random.uniform(k, s, minval=1e-7, maxval=1.0)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs)))

    def log_prob(self, value):
        v = _raw(value)
        return Tensor(v * jnp.log1p(-self.probs) + jnp.log(self.probs))


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _raw(loc)
        self.scale = _raw(scale)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.loc), jnp.shape(self.scale)))

    def sample(self, shape=()):
        k = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.cauchy(k, s))

    rsample = sample

    def log_prob(self, value):
        z = (_raw(value) - self.loc) / self.scale
        return Tensor(-jnp.log(math.pi * self.scale * (1 + z * z)))

    def entropy(self):
        return Tensor(jnp.broadcast_to(
            jnp.log(4 * math.pi * self.scale), self.batch_shape))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _raw(loc)
        self.scale = _raw(scale)
        self._normal = Normal(loc, scale)
        super().__init__(self._normal.batch_shape)

    @property
    def mean(self):
        return Tensor(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return Tensor((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def sample(self, shape=()):
        return Tensor(jnp.exp(_raw(self._normal.sample(shape))))

    rsample = sample

    def log_prob(self, value):
        v = _raw(value)
        return Tensor(_raw(self._normal.log_prob(jnp.log(v))) - jnp.log(v))

    def entropy(self):
        return Tensor(_raw(self._normal.entropy()) + self.loc)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _raw(probs)
        super().__init__(jnp.shape(self.probs)[:-1],
                         jnp.shape(self.probs)[-1:])

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs)

    def sample(self, shape=()):
        k = default_generator.next_key()
        s = _shape(shape) + self.batch_shape
        logits = jnp.log(jnp.clip(self.probs, 1e-12))
        draws = jax.random.categorical(
            k, logits, shape=(self.total_count,) + s)
        n_cat = self.probs.shape[-1]
        onehot = jax.nn.one_hot(draws, n_cat)
        return Tensor(jnp.sum(onehot, axis=0))

    def log_prob(self, value):
        from jax.scipy.special import gammaln

        v = _raw(value)
        logp = jnp.log(jnp.clip(self.probs, 1e-12))
        return Tensor(gammaln(self.total_count + 1.0)
                      - jnp.sum(gammaln(v + 1.0), -1)
                      + jnp.sum(v * logp, -1))


# -- KL registry (reference distribution/kl.py) ------------------------------

_KL_REGISTRY: Dict[Tuple[Type, Type], Callable] = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    lp = jax.nn.log_softmax(p.logits, -1)
    lq = jax.nn.log_softmax(q.logits, -1)
    return Tensor(jnp.sum(jnp.exp(lp) * (lp - lq), -1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    a = jnp.clip(p.probs, 1e-7, 1 - 1e-7)
    b = jnp.clip(q.probs, 1e-7, 1 - 1e-7)
    return Tensor(a * (jnp.log(a) - jnp.log(b))
                  + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    from jax.scipy.special import betaln, digamma

    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    t = (betaln(a2, b2) - betaln(a1, b1)
         + (a1 - a2) * digamma(a1) + (b1 - b2) * digamma(b1)
         + (a2 - a1 + b2 - b1) * digamma(a1 + b1))
    return Tensor(t)


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return Tensor(jnp.log(p.rate) - jnp.log(q.rate) + r - 1)


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions (reference
    distribution/exponential_family.py): entropy via the Bregman identity
    over the log-normalizer when subclasses provide natural parameters."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        import jax

        nat = self._natural_parameters
        lognorm = self._log_normalizer(*nat)
        result = lognorm - sum(
            (n * g).sum() if hasattr(n, "sum") else n * g
            for n, g in zip(nat, jax.grad(
                lambda *p: self._log_normalizer(*p).sum()
                if hasattr(self._log_normalizer(*p), "sum")
                else self._log_normalizer(*p), argnums=tuple(
                    range(len(nat))))(*nat)))
        return result - self._mean_carrier_measure


class Independent(Distribution):
    """Reinterpret batch dims as event dims (reference
    distribution/independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self._base = base
        self._rank = int(reinterpreted_batch_rank)
        super().__init__()

    @property
    def mean(self):
        return self._base.mean

    @property
    def variance(self):
        return self._base.variance

    def sample(self, shape=()):
        return self._base.sample(shape)

    def rsample(self, shape=()):
        return self._base.rsample(shape)

    def _sum_rightmost(self, x):
        import jax.numpy as jnp

        v = x.value if hasattr(x, "value") else jnp.asarray(x)
        for _ in range(self._rank):
            v = v.sum(-1)
        from ..core.tensor import Tensor

        return Tensor(v)

    def log_prob(self, value):
        return self._sum_rightmost(self._base.log_prob(value))

    def entropy(self):
        return self._sum_rightmost(self._base.entropy())


class TransformedDistribution(Distribution):
    """base distribution pushed through a chain of transforms (reference
    distribution/transformed_distribution.py)."""

    def __init__(self, base, transforms):
        self._base = base
        self._transforms = list(transforms)
        super().__init__()

    def sample(self, shape=()):
        x = self._base.sample(shape)
        for t in self._transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = getattr(self._base, "rsample", self._base.sample)(shape)
        for t in self._transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        lp = None
        y = value
        for t in reversed(self._transforms):
            x = t.inverse(y)
            ladj = t.forward_log_det_jacobian(x)
            ladj_v = ladj.value if hasattr(ladj, "value") else ladj
            lp = (-ladj_v) if lp is None else lp - ladj_v
            y = x
        base_lp = self._base.log_prob(y)
        base_v = base_lp.value if hasattr(base_lp, "value") else base_lp
        return Tensor(base_v + (0 if lp is None else lp))


__all__ += ["ExponentialFamily", "Independent", "TransformedDistribution"]


# -- transforms (reference distribution/__init__.py:15,29-30,56) -----------
from . import transform  # noqa: E402
from .transform import (AbsTransform, AffineTransform,  # noqa: E402,F401
                        ChainTransform, ExpTransform, IndependentTransform,
                        PowerTransform, ReshapeTransform, SigmoidTransform,
                        SoftmaxTransform, StackTransform,
                        StickBreakingTransform, TanhTransform, Transform)

__all__ += ["transform"] + transform.__all__
