"""Random-variable transforms (reference: distribution/transform.py —
13-class family, __all__ at :28, base Transform at :59).

A Transform is a differentiable injective map f with a tractable log-det-
Jacobian; pushing a base distribution through a chain of them yields
``TransformedDistribution`` with
``log p_Y(y) = log p_X(f^{-1}(y)) - log|det J_f(f^{-1}(y))|``.

TPU-native: every op is jnp (jit/vmap/grad-safe — no data-dependent Python
branching), values round-trip as framework Tensors.
"""
from __future__ import annotations

import enum
import functools
import math
import operator
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


class Type(enum.Enum):
    """Mapping types (reference transform.py:45)."""
    BIJECTION = "bijection"       # injective + surjective
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"

    @classmethod
    def is_injective(cls, t) -> bool:
        return t in (cls.BIJECTION, cls.INJECTION)


def _raw(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x)


def _wrap(v) -> Tensor:
    return Tensor(v)


class Transform:
    _type = Type.INJECTION

    # -- public API --------------------------------------------------------
    @classmethod
    def _is_injective(cls) -> bool:
        return Type.is_injective(cls._type)

    def __call__(self, input):
        if isinstance(input, Transform):
            return ChainTransform([input, self])
        from . import Distribution, TransformedDistribution

        if isinstance(input, Distribution):
            return TransformedDistribution(input, [self])
        return self.forward(input)

    def forward(self, x):
        return _wrap(self._forward(_raw(x)))

    def inverse(self, y):
        return _wrap(self._inverse(_raw(y)))

    def forward_log_det_jacobian(self, x):
        return _wrap(self._call_forward_ldj(_raw(x)))

    def inverse_log_det_jacobian(self, y):
        return _wrap(self._call_inverse_ldj(_raw(y)))

    def forward_shape(self, shape: Sequence[int]) -> Tuple[int, ...]:
        return tuple(self._forward_shape(tuple(shape)))

    def inverse_shape(self, shape: Sequence[int]) -> Tuple[int, ...]:
        return tuple(self._inverse_shape(tuple(shape)))

    # -- hooks -------------------------------------------------------------
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _call_forward_ldj(self, x):
        try:
            return self._forward_log_det_jacobian(x)
        except NotImplementedError:
            # raw inverse hook only — calling _call_inverse_ldj here would
            # recurse forever when neither hook is implemented
            return -self._inverse_log_det_jacobian(self._forward(x))

    def _call_inverse_ldj(self, y):
        try:
            return self._inverse_log_det_jacobian(y)
        except NotImplementedError:
            # route through _call_forward_ldj (NOT the raw hook): chain/
            # stack combinators only override the _call_ layer, and their
            # members' ldj support must surface here
            return -self._call_forward_ldj(self._inverse(y))

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def _inverse_log_det_jacobian(self, y):
        raise NotImplementedError

    def _forward_shape(self, shape):
        return shape

    def _inverse_shape(self, shape):
        return shape


class AbsTransform(Transform):
    r"""y = |x| — surjective, not injective; inverse picks the positive
    branch (reference AbsTransform:342 semantics: inverse(y) -> (−y, y)
    conceptually, value form returns the positive preimage)."""
    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y


class AffineTransform(Transform):
    r"""y = loc + scale·x (reference :414)."""
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        super().__init__()
        self._loc = _raw(loc)
        self._scale = _raw(scale)

    @property
    def loc(self):
        return _wrap(self._loc)

    @property
    def scale(self):
        return _wrap(self._scale)

    def _forward(self, x):
        return self._loc + self._scale * x

    def _inverse(self, y):
        return (y - self._loc) / self._scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self._scale)), x.shape)


class ExpTransform(Transform):
    r"""y = exp(x) (reference :621)."""
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    r"""y = x^p on the positive half-line (reference :765)."""
    _type = Type.BIJECTION

    def __init__(self, power):
        super().__init__()
        self._power = _raw(power)

    @property
    def power(self):
        return _wrap(self._power)

    def _forward(self, x):
        return jnp.power(x, self._power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self._power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self._power * jnp.power(x, self._power - 1)))


class SigmoidTransform(Transform):
    r"""y = 1/(1+exp(-x)) (reference :952)."""
    _type = Type.BIJECTION

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        # log sigmoid'(x) = -softplus(-x) - softplus(x)
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    r"""y = tanh(x) (reference :1237)."""
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh^2 x) = 2(log2 - x - softplus(-2x)) — numerically
        # stable for large |x|
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    r"""x -> softmax(x) over the last axis (reference :995). Not a
    bijection (softmax is shift-invariant); inverse is log(y) up to an
    additive constant."""
    _type = Type.OTHER

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_shape(self, shape):
        if len(shape) < 1:
            raise ValueError("SoftmaxTransform needs rank >= 1")
        return shape

    _inverse_shape = _forward_shape


class StickBreakingTransform(Transform):
    r"""Unconstrained R^{K-1} -> open simplex Δ^{K-1} by stick-breaking
    (reference :1171): each sigmoid(x_i − log(K−1−i)) breaks off a fraction
    of the remaining stick; the last coordinate is the leftover."""
    _type = Type.BIJECTION

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        zpad = jnp.concatenate(
            [z, jnp.ones(x.shape[:-1] + (1,), x.dtype)], -1)
        cum = jnp.cumprod(1 - z, -1)
        cumpad = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype), cum], -1)
        return zpad * cumpad

    def _inverse(self, y):
        y_crop = y[..., :-1]
        k = y_crop.shape[-1]
        # same offsets as _forward (k sticks: log(k), ..., log(1))
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=y.dtype))
        rem = 1.0 - jnp.cumsum(y_crop, -1)
        prev_rem = jnp.concatenate(
            [jnp.ones(y.shape[:-1] + (1,), y.dtype), rem[..., :-1]], -1)
        z = y_crop / prev_rem
        return jnp.log(z) - jnp.log1p(-z) + offset

    def _forward_log_det_jacobian(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        t = x - offset
        z = jax.nn.sigmoid(t)
        cum = jnp.cumprod(1 - z, -1)
        prev = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype), cum[..., :-1]], -1)
        # dy_i/dz_i = prev_rem_i; dz_i/dx_i = sigmoid'(t_i)
        return jnp.sum(jnp.log(prev) - jax.nn.softplus(-t)
                       - jax.nn.softplus(t), -1)

    def _forward_shape(self, shape):
        return shape[:-1] + (shape[-1] + 1,)

    def _inverse_shape(self, shape):
        return shape[:-1] + (shape[-1] - 1,)


class IndependentTransform(Transform):
    r"""Reinterpret the rightmost ``reinterpreted_batch_rank`` dims as event
    dims: the log-det sums over them (reference :670)."""

    def __init__(self, base: Transform, reinterpreted_batch_rank: int):
        super().__init__()
        self._base = base
        self._rank = int(reinterpreted_batch_rank)

    def _forward(self, x):
        return self._base._forward(x)

    def _inverse(self, y):
        return self._base._inverse(y)

    def _call_forward_ldj(self, x):
        ldj = self._base._call_forward_ldj(x)
        return jnp.sum(ldj, axis=tuple(range(-self._rank, 0)))

    def _call_inverse_ldj(self, y):
        ldj = self._base._call_inverse_ldj(y)
        return jnp.sum(ldj, axis=tuple(range(-self._rank, 0)))

    def _forward_shape(self, shape):
        return self._base._forward_shape(shape)

    def _inverse_shape(self, shape):
        return self._base._inverse_shape(shape)


class ReshapeTransform(Transform):
    r"""Reshape the event part (reference :829)."""
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        super().__init__()
        self._in = tuple(int(s) for s in in_event_shape)
        self._out = tuple(int(s) for s in out_event_shape)
        if functools.reduce(operator.mul, self._in, 1) != functools.reduce(
                operator.mul, self._out, 1):
            raise ValueError(
                f"in_event_shape {self._in} and out_event_shape "
                f"{self._out} have different sizes")

    @property
    def in_event_shape(self):
        return self._in

    @property
    def out_event_shape(self):
        return self._out

    def _forward(self, x):
        batch = x.shape[: x.ndim - len(self._in)]
        return jnp.reshape(x, batch + self._out)

    def _inverse(self, y):
        batch = y.shape[: y.ndim - len(self._out)]
        return jnp.reshape(y, batch + self._in)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[: x.ndim - len(self._in)]
        return jnp.zeros(batch, x.dtype)

    def _forward_shape(self, shape):
        n = len(self._in)
        if tuple(shape[len(shape) - n:]) != self._in:
            raise ValueError(f"shape {shape} does not end in {self._in}")
        return tuple(shape[: len(shape) - n]) + self._out

    def _inverse_shape(self, shape):
        n = len(self._out)
        if tuple(shape[len(shape) - n:]) != self._out:
            raise ValueError(f"shape {shape} does not end in {self._out}")
        return tuple(shape[: len(shape) - n]) + self._in


class ChainTransform(Transform):
    r"""Composition f = f_n ∘ ... ∘ f_1 (reference :496); log-det adds."""

    def __init__(self, transforms: Sequence[Transform]):
        super().__init__()
        self._transforms = list(transforms)

    @property
    def transforms(self):
        return list(self._transforms)

    def _is_injective(self) -> bool:
        # injective iff every member is (reference ChainTransform)
        return all(t._is_injective() for t in self._transforms)

    def _forward(self, x):
        for t in self._transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self._transforms):
            y = t._inverse(y)
        return y

    def _call_forward_ldj(self, x):
        total = 0.0
        for t in self._transforms:
            total = total + t._call_forward_ldj(x)
            x = t._forward(x)
        return total

    def _forward_shape(self, shape):
        for t in self._transforms:
            shape = t._forward_shape(shape)
        return shape

    def _inverse_shape(self, shape):
        for t in reversed(self._transforms):
            shape = t._inverse_shape(shape)
        return shape


class StackTransform(Transform):
    r"""Apply a sequence of transforms to slices along ``axis`` (reference
    :1051): slice i gets transforms[i]."""

    def __init__(self, transforms: Sequence[Transform], axis: int = 0):
        super().__init__()
        self._transforms = list(transforms)
        self._axis = int(axis)

    @property
    def transforms(self):
        return list(self._transforms)

    @property
    def axis(self):
        return self._axis

    def _split(self, v):
        n = len(self._transforms)
        return [jnp.squeeze(s, self._axis)
                for s in jnp.split(v, n, axis=self._axis)]

    def _forward(self, x):
        outs = [t._forward(s)
                for t, s in zip(self._transforms, self._split(x))]
        return jnp.stack(outs, self._axis)

    def _inverse(self, y):
        outs = [t._inverse(s)
                for t, s in zip(self._transforms, self._split(y))]
        return jnp.stack(outs, self._axis)

    def _call_forward_ldj(self, x):
        outs = [t._call_forward_ldj(s)
                for t, s in zip(self._transforms, self._split(x))]
        return jnp.stack(outs, self._axis)
