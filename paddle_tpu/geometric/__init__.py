"""paddle.geometric parity — graph-NN message passing and sampling
(reference: python/paddle/geometric/: message_passing/send_recv.py,
sampling/neighbors.py, reindex.py).

TPU-native design: all message passing lowers to ``jax.ops.segment_*``
(XLA scatter-reduce — the MXU-free path the TPU handles well); neighbor
sampling is host-side numpy (the reference runs it on CPU threads too — it
is a data-pipeline step, not a device kernel).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ..ops._helpers import nondiff_op, unwrap

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "send_uv", "sample_neighbors",
           "weighted_sample_neighbors", "reindex_graph",
           "reindex_heter_graph"]


def _nseg(ids, count):
    if count is not None:
        return int(count)
    return int(np.asarray(unwrap(ids)).max()) + 1 if np.asarray(
        unwrap(ids)).size else 0


def segment_sum(data, segment_ids, name=None):
    n = _nseg(segment_ids, None)
    return apply_op(
        lambda d, i: jax.ops.segment_sum(d, i, num_segments=n),
        data, segment_ids, op_name="segment_sum")


def segment_mean(data, segment_ids, name=None):
    n = _nseg(segment_ids, None)

    def f(d, i):
        s = jax.ops.segment_sum(d, i, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones_like(d[..., :1]), i, num_segments=n)
        return s / jnp.maximum(c, 1)

    return apply_op(f, data, segment_ids, op_name="segment_mean")


def segment_max(data, segment_ids, name=None):
    n = _nseg(segment_ids, None)
    return apply_op(
        lambda d, i: jax.ops.segment_max(d, i, num_segments=n),
        data, segment_ids, op_name="segment_max")


def segment_min(data, segment_ids, name=None):
    n = _nseg(segment_ids, None)
    return apply_op(
        lambda d, i: jax.ops.segment_min(d, i, num_segments=n),
        data, segment_ids, op_name="segment_min")


_POOLS = {
    "sum": jax.ops.segment_sum,
    "add": jax.ops.segment_sum,
    "mean": None,  # composed
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src], scatter-reduce onto dst (send_recv.py send_u_recv)."""
    n = out_size or (unwrap(x).shape[0])

    def f(xv, si, di):
        msg = xv[si]
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msg, di, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones((msg.shape[0], 1), xv.dtype),
                                    di, num_segments=n)
            return s / jnp.maximum(c, 1)
        return _POOLS[reduce_op](msg, di, num_segments=n)

    return apply_op(f, x, src_index, dst_index, op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine node features with edge features, then scatter-reduce."""
    n = out_size or (unwrap(x).shape[0])

    def f(xv, yv, si, di):
        msg = xv[si]
        if message_op == "add":
            msg = msg + yv
        elif message_op == "sub":
            msg = msg - yv
        elif message_op == "mul":
            msg = msg * yv
        elif message_op == "div":
            msg = msg / yv
        else:
            raise ValueError(f"unknown message_op {message_op}")
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msg, di, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones((msg.shape[0], 1), msg.dtype),
                                    di, num_segments=n)
            return s / jnp.maximum(c, 1)
        return _POOLS[reduce_op](msg, di, num_segments=n)

    return apply_op(f, x, y, src_index, dst_index, op_name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints (send_recv.py send_uv)."""

    def f(xv, yv, si, di):
        a, b = xv[si], yv[di]
        if message_op == "add":
            return a + b
        if message_op == "sub":
            return a - b
        if message_op == "mul":
            return a * b
        if message_op == "div":
            return a / b
        raise ValueError(f"unknown message_op {message_op}")

    return apply_op(f, x, y, src_index, dst_index, op_name="send_uv")


def _csr_neighbors(row, colptr, nodes):
    row = np.asarray(unwrap(row))
    colptr = np.asarray(unwrap(colptr))
    nodes = np.asarray(unwrap(nodes))
    return row, colptr, nodes


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling over CSC graph (sampling/neighbors.py).
    Host-side (data pipeline step). Returns (out_neighbors, out_count[, eids])."""
    r, cp, nodes = _csr_neighbors(row, colptr, input_nodes)
    rng = np.random.RandomState()
    outs, counts, out_eids = [], [], []
    ev = np.asarray(unwrap(eids)) if eids is not None else None
    for nd in nodes:
        beg, end = int(cp[nd]), int(cp[nd + 1])
        neigh = r[beg:end]
        ids = np.arange(beg, end)
        if 0 <= sample_size < len(neigh):
            pick = rng.choice(len(neigh), size=sample_size, replace=False)
            neigh = neigh[pick]
            ids = ids[pick]
        outs.append(neigh)
        counts.append(len(neigh))
        if return_eids and ev is not None:
            out_eids.append(ev[ids])
    out = Tensor(jnp.asarray(np.concatenate(outs) if outs else
                             np.zeros((0,), r.dtype)))
    cnt = Tensor(jnp.asarray(np.asarray(counts, np.int32)))
    if return_eids:
        return out, cnt, Tensor(jnp.asarray(
            np.concatenate(out_eids) if out_eids else np.zeros((0,), r.dtype)))
    return out, cnt


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weight-proportional sampling (sampling/neighbors.py weighted variant)."""
    r, cp, nodes = _csr_neighbors(row, colptr, input_nodes)
    w = np.asarray(unwrap(edge_weight), np.float64)
    rng = np.random.RandomState()
    outs, counts, out_eids = [], [], []
    ev = np.asarray(unwrap(eids)) if eids is not None else None
    for nd in nodes:
        beg, end = int(cp[nd]), int(cp[nd + 1])
        neigh = r[beg:end]
        ids = np.arange(beg, end)
        if 0 <= sample_size < len(neigh):
            p = w[beg:end]
            p = p / p.sum() if p.sum() > 0 else None
            pick = rng.choice(len(neigh), size=sample_size, replace=False,
                              p=p)
            neigh = neigh[pick]
            ids = ids[pick]
        outs.append(neigh)
        counts.append(len(neigh))
        if return_eids and ev is not None:
            out_eids.append(ev[ids])
    out = Tensor(jnp.asarray(np.concatenate(outs) if outs else
                             np.zeros((0,), r.dtype)))
    cnt = Tensor(jnp.asarray(np.asarray(counts, np.int32)))
    if return_eids:
        return out, cnt, Tensor(jnp.asarray(
            np.concatenate(out_eids) if out_eids else np.zeros((0,), r.dtype)))
    return out, cnt


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact subgraph node ids to 0..n (reindex.py reindex_graph).
    Returns (reindexed_src, reindexed_dst, out_nodes)."""
    xv = np.asarray(unwrap(x))
    nb = np.asarray(unwrap(neighbors))
    ct = np.asarray(unwrap(count))
    seen = {int(v): i for i, v in enumerate(xv)}
    order = list(xv)
    for v in nb:
        vi = int(v)
        if vi not in seen:
            seen[vi] = len(order)
            order.append(vi)
    src = np.asarray([seen[int(v)] for v in nb], np.int64)
    dst = np.repeat(np.arange(len(xv)), ct).astype(np.int64)
    return (Tensor(jnp.asarray(src)), Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(np.asarray(order, xv.dtype))))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: neighbors/count are per-edge-type lists."""
    xv = np.asarray(unwrap(x))
    seen = {int(v): i for i, v in enumerate(xv)}
    order = list(xv)
    srcs, dsts = [], []
    for nb_t, ct_t in zip(neighbors, count):
        nb = np.asarray(unwrap(nb_t))
        ct = np.asarray(unwrap(ct_t))
        for v in nb:
            vi = int(v)
            if vi not in seen:
                seen[vi] = len(order)
                order.append(vi)
        srcs.append(np.asarray([seen[int(v)] for v in nb], np.int64))
        dsts.append(np.repeat(np.arange(len(xv)), ct).astype(np.int64))
    return ([Tensor(jnp.asarray(s)) for s in srcs],
            [Tensor(jnp.asarray(d)) for d in dsts],
            Tensor(jnp.asarray(np.asarray(order, xv.dtype))))
