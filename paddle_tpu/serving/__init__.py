"""paddle_tpu.serving — online continuous-batching serving layer.

The reference ships a dedicated inference/serving capability layer
(``paddle/fluid/inference`` + the server stack above AnalysisPredictor);
our reproduction's engines (`paddle_tpu.inference.generation`) stop at a
stepwise API — ``add_request`` / ``decode_segment`` /
``collect_finished`` — plus a synchronous batch ``serve()``. THIS
package is the first layer a real client can talk to:

- :class:`~paddle_tpu.serving.queue.RequestQueue` — bounded, priority-
  and deadline-aware admission queue (backpressure: a full queue rejects
  with reason, the HTTP 429 path);
- :class:`~paddle_tpu.serving.queue.RequestHandle` — per-request
  blocking ``result()``, incremental token ``stream()`` iterator, and
  ``cancel()`` (the slot — and its KV pages — is reclaimed at the next
  inter-segment gap, not leaked);
- :class:`~paddle_tpu.serving.scheduler.Server` — the scheduler thread
  that owns an engine and drives Orca-style iteration-level scheduling:
  admit in the inter-segment gap via the engine's public capacity probe
  (``can_admit`` / ``free_slots``), decode one jitted segment, stream
  new tokens, retire finished/cancelled/expired work;
- :func:`~paddle_tpu.serving.http.serve_http` — stdlib HTTP front-end
  (``POST /generate`` with chunked ndjson streaming, ``GET /healthz``,
  and the monitor package's ``/metrics`` exporters).

Fault isolation (see README "Failure modes & recovery"): faults are
classified by blast radius
(:class:`~paddle_tpu.inference.generation.RequestFault` /
:class:`~paddle_tpu.inference.generation.EngineFault` /
:func:`~paddle_tpu.inference.generation.classify_fault`, re-exported
here) — a request-scoped fault fails ONLY that request with its cause;
an engine-scoped one triggers supervised recovery
(``engine.reset_state()`` + replay of in-flight requests, bounded by
``Server(max_restarts=..., max_replays=...)``); a stalled step is
caught by the ``stall_timeout_s`` watchdog and surfaces as the
``degraded`` status (healthz 503, submissions reject with reason).
``paddle_tpu.testing.faults`` is the deterministic injection harness
the chaos suite drives all of this with.

Memory pressure (README "Memory pressure"): with a paged engine in
``admission_mode="optimistic"`` the pool admits on ACTUAL usage
(prompt + one page, grown per gap) instead of the worst case; when
growth outruns the pool the scheduler preempts victims — lowest
priority first, then youngest, never the oldest survivor — and
replays them later with their generated tokens intact (greedy
preempt-resume is bitwise-identical). Rails:
``Server(max_preemptions=...)`` fails a thrashing request with
:class:`~paddle_tpu.serving.scheduler.PreemptionBudgetExceeded`, the
engine's ``kv_watermark`` pauses new admissions under crowding, and a
request the pool cannot hold even alone fails alone with
:class:`~paddle_tpu.inference.generation.PagePoolExhausted` as its
typed cause. ``Server.pressure()`` / the ``/healthz`` ``pressure``
field expose occupancy, waiting-on-pages, and the preemption total.

Fleet serving (README "Fleet serving"): :class:`Router` spreads
requests over N replica Servers built from a :class:`ReplicaSpec` —
health- and load-aware routing off each replica's lock-light
``Server.load()`` snapshot, per-replica circuit breakers (open /
half-open probe / close), FAILOVER REPLAY (a request whose replica
dies or degrades mid-flight resubmits elsewhere as prompt + streamed
tokens; greedy failover is bitwise-identical, the
:class:`RouterHandle` keeps one stable rid and one uninterrupted
``stream()``; bounded by ``max_failovers`` →
:class:`FailoverBudgetExceeded`), supervised replica restarts with
exponential backoff, and ``drain(i)`` / ``rolling_restart()`` for
zero-downtime rollouts. ``serve_http(router)`` serves the same routes
with fleet-aggregated ``/healthz``.

Multi-tenant LoRA (README "Multi-tenant LoRA serving"): engines built
with ``lora_capacity=K`` serve up to K resident fine-tunes from ONE
compiled program set — stacked factor banks gathered per slot by an
``adapter_idx`` device vector (:class:`AdapterRegistry` owns the bank
+ hot load/unload with deferral), ``GenerationConfig(adapter=...)`` /
the HTTP ``adapter`` field select per request, prefix-cache namespaces
are adapter-salted (cross-adapter warm hits structurally zero),
``Server(tenant_quotas=...)`` caps per-tenant admissions without
starving other tenants, and the Router prefers adapter-resident
replicas.

SLO & goodput (README "SLO & goodput"): every Server carries a
``paddle_tpu.monitor.slo.SLOTracker`` — mergeable fixed-log-bucket
latency digests per (metric, tenant) for TTFT/TPOT/queue-wait/e2e
plus per-tenant token/KV-page-second cost accounting, fed only while
``FLAGS_enable_monitor`` is on. ``Server(slo_policy=SLOPolicy(...))``
scores every service-terminal request into per-tenant GOODPUT
(fraction meeting the thresholds) and fast/slow BURN-RATE windows.
``GET /stats`` (Server or Router front) serves the rollup; the
Router's version MERGES replica digests — exact fleet percentiles,
never averages — and runs the slow-replica SKEW DETECTOR (rolling
TPOT p50 vs fleet median; ``slow`` deprioritizes routing without
opening a breaker).

Cross-process fleet (README "Fleet serving", DESIGN "Fleet
topology"): :class:`RemoteReplica` is a Server-shaped CLIENT for an
out-of-process replica speaking the same HTTP surface — the Router
consumes it through the identical duck-typed seam (zero forks:
breakers, skew detection, failover replay, adapter affinity all work
across processes), and :class:`RemoteReplicaSpec` makes supervised
restart a process respawn. On top, ``paddle_tpu.serving.remote``
implements disaggregated prefill/decode:
:class:`~paddle_tpu.serving.remote.DisaggregatedFront` runs chunked
prefill to completion on one replica, ships the finished KV pages
(int8 + per-page scales, chain hashes included) over
``POST /kv/export`` → ``POST /kv/import`` to a decode replica —
idempotent and dedup-able by the prefix-cache chain hash, a page copy
never a format conversion — and byte-identity with the monolithic
engine is the test bar.

Tracing & flight recorder (README "Tracing & flight recorder"): with
``FLAGS_enable_trace`` on, every lifecycle seam records a structured
event into ``paddle_tpu.tracing``'s bounded ring — read one request's
timeline via ``RequestHandle.timeline()`` /
``Server.request_timeline(rid)`` / HTTP ``GET /trace?rid=``, export
Chrome-trace/Perfetto JSON, and collect the automatic flight-recorder
dumps (engine faults, watchdog ``degraded`` flips, preemption storms)
from ``Server.fault_stats()["flight_dumps"]`` or ``/healthz``'s
``flight_dump`` field.

Quick start::

    import paddle_tpu.serving as serving
    from paddle_tpu.inference.generation import (
        GenerationConfig, PagedContinuousBatchingEngine)

    eng = PagedContinuousBatchingEngine(model, max_batch=4,
                                        num_pages=64, page_size=16,
                                        max_pages=32)
    srv = serving.Server(eng, max_queue=64, segment_steps=8)
    httpd = serving.serve_http(srv, port=8000)

    h = srv.submit(prompt_ids, GenerationConfig(max_new_tokens=64))
    for tok in h.stream():
        ...
"""
from ..inference.generation import (EngineFault, PagePoolExhausted,
                                    RequestFault, classify_fault)
from ..monitor.slo import SLOPolicy
from .adapters import AdapterRegistry
from .control import (RUNG_ACTIONS, ControlPlane, ControlPolicy,
                      ElasticController)
from .http import serve_http
from .queue import (CANCELLED, EXPIRED, FAILED, FINISHED, QUEUED,
                    RUNNING, DeadlineExpired, QueueFull,
                    RequestCancelled, RequestFailed, RequestHandle,
                    RequestQueue, RequestRejected)
from .remote import (DisaggregatedFront, KVIntegrityError,
                     RemoteReplica, RemoteReplicaSpec)
from .router import (FailoverBudgetExceeded, FleetUnavailable,
                     ReplicaSpec, Router, RouterHandle)
from .scheduler import PreemptionBudgetExceeded, Server

__all__ = [
    "Server", "serve_http", "RequestHandle", "RequestQueue",
    "AdapterRegistry",
    "RequestRejected", "QueueFull", "RequestCancelled",
    "DeadlineExpired", "RequestFailed",
    "RequestFault", "EngineFault", "classify_fault",
    "PagePoolExhausted", "PreemptionBudgetExceeded",
    "Router", "ReplicaSpec", "RouterHandle",
    "RemoteReplica", "RemoteReplicaSpec", "DisaggregatedFront",
    "KVIntegrityError",
    "FailoverBudgetExceeded", "FleetUnavailable", "SLOPolicy",
    "ControlPolicy", "ControlPlane", "ElasticController",
    "RUNG_ACTIONS",
    "QUEUED", "RUNNING", "FINISHED", "CANCELLED", "EXPIRED", "FAILED",
]
