"""Cross-process fleet: HTTP replicas behind the same Router seam.

Everything the Router consumes from a replica is duck-typed (see
``router.py`` — "anything with ``build()`` -> Server-shaped object
routes"), and everything a Server exposes is already on the wire:
``/generate`` streams tokens, ``/healthz`` is ``Server.load()``
verbatim, ``/stats?shard=1`` is the mergeable ``digests_dict()``
shard, ``/profile`` is the program-ledger shard. This module closes
the loop with:

- :class:`RemoteReplica` — a Server-shaped **client**: ``submit()``
  POSTs a streaming ``/generate`` and relays the ndjson stream into a
  local :class:`~paddle_tpu.serving.queue.RequestHandle`; ``load()``/
  ``status``/``queue.depth``/``num_active()``/``engine.*`` read a
  background-polled ``/healthz`` snapshot (NEVER the network — the
  router's pick loop runs under its lock); ``slo``/``profile()`` pull
  the raw shards so the fleet rollup stays merge-exact. Breakers,
  slow-replica skew detection, failover replay and adapter-affinity
  routing work unchanged — zero Router forks.
- :class:`RemoteReplicaSpec` — a :class:`~.router.ReplicaSpec` whose
  ``build()`` spawns (or attaches to) a replica **process**; the
  Router's supervised restart becomes a respawn.
- ``encode_kv_payload``/``decode_kv_payload`` — the ``/kv/export`` →
  ``/kv/import`` octet-stream framing for disaggregated
  prefill/decode: finished KV pages (int8 + per-page scales included)
  ship as raw pool bytes under a JSON header carrying the prefix-cache
  chain hashes. A page COPY, never a format conversion — and the chain
  hashes make the import idempotent and dedup-able fleet-wide.
- :class:`DisaggregatedFront` — Splitwise/DistServe-shaped serving:
  a prefill replica runs chunked prefill to completion (budget 1),
  its finished pages ship to the decode replica, and decode continues
  from the warm prefix. If the decode replica dies mid-stream the
  front replays ``prompt + tokens emitted so far`` on the prefill
  replica — the same causal-replay argument (and byte-identity bar)
  as the in-process failover.
- ``python -m paddle_tpu.serving.remote`` — the replica entrypoint:
  builds a seeded toy Server, serves HTTP, prints the bound port.

Every socket here carries an explicit timeout (lint PT006): a replica
that stops answering must surface as a breaker/failover event, never
as a hung router thread.
"""

import hashlib
import json
import os
import select
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import List, Optional

import numpy as np

from .. import tracing as trace
from ..inference.generation import (GenerationConfig, PagePoolExhausted,
                                    _prompt_len)
from .queue import (CANCELLED, EXPIRED, FAILED, FINISHED, RequestFailed,
                    RequestHandle, RequestRejected)
from .router import ReplicaSpec
from .scheduler import PreemptionBudgetExceeded

__all__ = ["RemoteReplica", "RemoteReplicaSpec", "DisaggregatedFront",
           "KVIntegrityError", "encode_kv_payload", "decode_kv_payload",
           "spawn_replica"]


# ---------------------------------------------------------------------------
# KV payload wire framing (/kv/export response == /kv/import request)
# ---------------------------------------------------------------------------
# [4-byte big-endian header length][JSON header][raw array bytes...]
#
# The header carries everything except the page bytes: version, the
# pool's kv_dtype + page_size, the export salt, the prefix-cache chain
# (hash, parent, tokens) per block, and per-layer array metadata
# (dtype name + shape). The arrays follow concatenated, C-contiguous,
# per layer in the fixed order k, v[, k_scale, v_scale]. JSON never
# touches the page bytes (a 2 MB page would balloon 4x as a number
# list and lose its dtype), and the receiver can validate the whole
# geometry before reading a single array byte.

_KV_MAGIC_VERSION = 1
_MAX_KV_HEADER_BYTES = 8 << 20
_ARRAY_KEYS = ("k", "v", "k_scale", "v_scale")
_KV_DIGEST_BYTES = 16


class KVIntegrityError(ValueError):
    """A KV payload arrived well-framed but WRONG: a checksum over the
    page bytes disagrees with the header's digests. Distinct from the
    plain framing ``ValueError`` so the import path can count it and
    the shipper can re-ship (chain-hash dedup makes the retry
    idempotent) instead of treating it as a validation bug."""


def _kv_digest(*parts: bytes) -> str:
    h = hashlib.blake2b(digest_size=_KV_DIGEST_BYTES)
    for p in parts:
        h.update(p)
    return h.hexdigest()


def _np_dtype(name: str) -> np.dtype:
    """dtype-by-name, including ``bfloat16`` (ml_dtypes registers it —
    jax always ships it, so this adds no dependency)."""
    if name == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _block_hash_bytes(blocks) -> List[bytes]:
    """The chain hashes as bytes, defensively (a digestless manual
    payload may carry anything here — encode and decode must agree on
    the fallback so round-trips stay verifiable)."""
    out = []
    for b in (blocks if isinstance(blocks, list) else []):
        try:
            out.append(bytes.fromhex(b.get("hash", "")))
        except (AttributeError, TypeError, ValueError):
            out.append(b"")
    return out


def encode_kv_payload(payload: dict) -> bytes:
    """Frame one ``engine.export_kv_pages()`` payload for the wire.

    The header carries integrity digests (``blake2b`` over the chain
    hashes + raw pool bytes): one whole-payload checksum plus — when
    every array's leading dim is the block count, which is how the
    engine exports — a per-block checksum that lets the importer NAME
    the corrupted block. ``decode_kv_payload`` verifies them before a
    single page can install; payloads without digests (older writers,
    hand-built tests) still decode."""
    header = {k: payload[k] for k in ("version", "kv_dtype",
                                      "page_size", "salt", "coverage",
                                      "blocks")}
    metas, chunks, arrays = [], [], []
    for lay in payload["layers"]:
        meta = {}
        for key in _ARRAY_KEYS:
            if key not in lay:
                continue
            arr = np.ascontiguousarray(lay[key])
            meta[key] = {"dtype": arr.dtype.name,
                         "shape": list(arr.shape)}
            chunks.append(arr.tobytes())
            arrays.append(arr)
        metas.append(meta)
    header["layers"] = metas
    hashes = _block_hash_bytes(payload["blocks"])
    digests = {"algo": f"blake2b-{_KV_DIGEST_BYTES}",
               "payload": _kv_digest(*hashes, *chunks)}
    nblocks = len(hashes)
    if nblocks and all(a.ndim >= 1 and a.shape[0] == nblocks
                       for a in arrays):
        digests["blocks"] = [
            _kv_digest(hashes[b],
                       *(np.ascontiguousarray(a[b]).tobytes()
                         for a in arrays))
            for b in range(nblocks)]
    header["digests"] = digests
    hdr = json.dumps(header).encode()
    return b"".join([len(hdr).to_bytes(4, "big"), hdr] + chunks)


def decode_kv_payload(raw: bytes) -> dict:
    """Parse the framing back into the ``import_kv_pages()`` payload
    shape. Validates the frame exhaustively — this is the one spot
    untrusted bytes become arrays, and a short/torn body must be a
    ValueError (HTTP 400), never a numpy surprise inside the
    scheduler's gap."""
    if len(raw) < 4:
        raise ValueError("KV payload too short for its header length")
    n = int.from_bytes(raw[:4], "big")
    if n <= 0 or n > _MAX_KV_HEADER_BYTES or 4 + n > len(raw):
        raise ValueError(f"KV payload header length {n} out of bounds")
    try:
        header = json.loads(raw[4:4 + n])
    except json.JSONDecodeError as e:
        raise ValueError(f"KV payload header is not JSON: {e}") from e
    if not isinstance(header, dict):
        raise ValueError("KV payload header must be a JSON object")
    if header.get("version") != _KV_MAGIC_VERSION:
        raise ValueError(
            f"KV payload version {header.get('version')!r} "
            f"(expected {_KV_MAGIC_VERSION})")
    for key in ("kv_dtype", "page_size", "salt", "coverage",
                "blocks", "layers"):
        if key not in header:
            raise ValueError(f"KV payload header missing {key!r}")
    out = {k: header[k] for k in ("version", "kv_dtype", "page_size",
                                  "salt", "coverage", "blocks")}
    if not isinstance(header["layers"], list):
        raise ValueError("KV payload 'layers' must be a list")
    # an integrity-protected payload (digests in the header) that
    # arrives SHORT is wire damage, not a malformed request: type it
    # so the shipper re-ships instead of treating the replica as
    # broken (KVIntegrityError subclasses ValueError — callers that
    # only know 400 semantics keep working)
    torn_exc = (KVIntegrityError
                if isinstance(header.get("digests"), dict)
                else ValueError)
    layers, off = [], 4 + n
    for li, meta in enumerate(header["layers"]):
        if not isinstance(meta, dict) or "k" not in meta \
                or "v" not in meta:
            raise ValueError(
                f"KV payload layer {li} metadata must carry 'k' "
                "and 'v'")
        lay = {}
        for key in _ARRAY_KEYS:
            if key not in meta:
                continue
            m = meta[key]
            try:
                dt = _np_dtype(m["dtype"])
                shape = tuple(int(s) for s in m["shape"])
            except (KeyError, TypeError, ValueError) as e:
                raise ValueError(
                    f"KV payload layer {li} {key!r} metadata "
                    f"malformed: {e}") from e
            if any(s < 0 for s in shape):
                raise ValueError(
                    f"KV payload layer {li} {key!r} has a negative "
                    "dim")
            nbytes = dt.itemsize * int(np.prod(shape, dtype=np.int64))
            if off + nbytes > len(raw):
                raise torn_exc(
                    f"KV payload truncated at layer {li} {key!r}")
            lay[key] = np.frombuffer(
                raw, dtype=dt, count=int(np.prod(shape,
                                                 dtype=np.int64)),
                offset=off).reshape(shape)
            off += nbytes
        layers.append(lay)
    if off != len(raw):
        raise torn_exc(
            f"KV payload carries {len(raw) - off} trailing bytes")
    dig = header.get("digests")
    if isinstance(dig, dict) and dig.get("payload"):
        # verify BEFORE anything can install: framing above proved the
        # geometry; this proves the bytes. The whole-payload digest is
        # one pass over the array region; per-block digests only
        # recompute on mismatch, to name the culprit.
        hashes = _block_hash_bytes(header["blocks"])
        if _kv_digest(*hashes, raw[4 + n:]) != dig["payload"]:
            bad = None
            blk_digs = dig.get("blocks")
            if isinstance(blk_digs, list) \
                    and len(blk_digs) == len(hashes):
                for b in range(len(hashes)):
                    parts = [hashes[b]]
                    for lay in layers:
                        for key in _ARRAY_KEYS:
                            if key in lay and lay[key].shape \
                                    and lay[key].shape[0] == len(hashes):
                                parts.append(np.ascontiguousarray(
                                    lay[key][b]).tobytes())
                    if _kv_digest(*parts) != blk_digs[b]:
                        bad = b
                        break
            raise KVIntegrityError(
                "KV payload integrity check failed"
                + (f" at block {bad}" if bad is not None else "")
                + ": checksum mismatch (bit-rot on the wire); "
                "nothing was installed — re-ship (chain-hash dedup "
                "makes the retry idempotent)")
    out["layers"] = layers
    return out


# ---------------------------------------------------------------------------
# HTTP plumbing (every call carries an explicit timeout)
# ---------------------------------------------------------------------------
def _http_json(method: str, url: str, path: str,
               body: Optional[dict] = None,
               timeout: float = 5.0):
    """One bounded JSON request; returns (status, parsed-body)."""
    import http.client
    from urllib.parse import urlsplit

    u = urlsplit(url)
    conn = http.client.HTTPConnection(u.hostname, u.port,
                                      timeout=timeout)
    try:
        payload = (None if body is None
                   else json.dumps(body).encode())
        headers = ({"Content-Type": "application/json"}
                   if payload is not None else {})
        conn.request(method, path, body=payload, headers=headers)
        resp = conn.getresponse()
        raw = resp.read()
        try:
            parsed = json.loads(raw) if raw else {}
        except json.JSONDecodeError:
            parsed = {"error": raw.decode("utf-8", "replace")}
        return resp.status, parsed
    finally:
        conn.close()


def _http_raw(method: str, url: str, path: str, body: bytes,
              ctype: str, timeout: float = 30.0):
    """One bounded raw-bytes request; returns (status, raw body)."""
    import http.client
    from urllib.parse import urlsplit

    u = urlsplit(url)
    conn = http.client.HTTPConnection(u.hostname, u.port,
                                      timeout=timeout)
    try:
        conn.request(method, path, body=body,
                     headers={"Content-Type": ctype})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# Server-shaped shims over the polled /healthz snapshot
# ---------------------------------------------------------------------------
class _RemoteQueue:
    """``.depth`` off the cached snapshot — the router's pick loop
    reads it under the router lock, so it must never do I/O."""
    __slots__ = ("_rep",)

    def __init__(self, rep):
        self._rep = rep

    @property
    def depth(self) -> int:  # lint: hot-path
        # lint: allow-host-sync(host dict read off the cached snapshot)
        return int(self._rep._snap().get("queue_depth", 0))


class _RemoteAlloc:
    __slots__ = ("_rep",)

    def __init__(self, rep):
        self._rep = rep

    @property
    def free_pages(self) -> int:  # lint: hot-path
        # lint: allow-host-sync(host dict read off the cached snapshot)
        return int(self._rep._snap().get("free_pages", 0))


class _RemoteAdapters:
    """Adapter-affinity membership test (``adapter in engine.adapters``)
    over the snapshot's ``lora.resident`` list."""
    __slots__ = ("_rep",)

    def __init__(self, rep):
        self._rep = rep

    def _resident(self) -> list:
        lora = self._rep._snap().get("lora")
        if isinstance(lora, dict):
            return list(lora.get("resident", []))
        return []

    def __contains__(self, name) -> bool:  # lint: hot-path
        return name in self._resident()

    def resident(self) -> list:
        return self._resident()


class _RemoteEngine:
    """The engine-shaped corner of the duck type: capacity fields the
    router reads per pick. ``close()`` is a no-op — the REMOTE process
    owns its engine; the replica's ``shutdown()`` owns the process."""
    __slots__ = ("_rep", "alloc", "adapters")

    def __init__(self, rep):
        self._rep = rep
        self.alloc = _RemoteAlloc(rep)
        self.adapters = _RemoteAdapters(rep)

    @property
    def max_len(self) -> int:
        return int(self._rep._snap().get("max_len", 1 << 30))

    @property
    def prefix_cache(self) -> bool:
        p = self._rep._snap().get("pressure")
        return bool(isinstance(p, dict) and p.get("prefix_cache"))

    def close(self) -> None:
        pass


class _RemoteSLO:
    """SLO-tracker shim: the raw ``digests_dict()`` shard comes over
    ``GET /stats?shard=1`` and everything derives from it LOCALLY by
    the same merge math — fleet percentiles stay exact because the
    wire carries buckets, never pre-rolled percentiles."""
    __slots__ = ("_rep",)

    def __init__(self, rep):
        self._rep = rep

    def digests_dict(self) -> dict:
        status, body = _http_json(
            "GET", self._rep.base_url, "/stats?shard=1",
            timeout=self._rep.io_timeout_s)
        if status != 200:
            raise RuntimeError(
                f"replica {self._rep.base_url} /stats?shard=1 -> "
                f"{status}: {body.get('error')}")
        return body

    def rolling_tpot_p50(self, min_count: int = 1) -> Optional[float]:
        from ..monitor.slo import LatencyDigest

        d = LatencyDigest.from_dict(
            self.digests_dict()["rolling_tpot"])
        if d.count < max(1, min_count):
            return None
        return d.percentile(50)

    def percentiles(self) -> dict:
        from ..monitor.slo import fleet_rollup

        return fleet_rollup([self.digests_dict()])["metrics"]


class RemoteReplica:
    """A Server-shaped client for one out-of-process replica.

    The router-facing read surface (``status`` / ``load()`` /
    ``queue.depth`` / ``num_active()`` / ``engine.*``) comes from a
    background-polled ``/healthz`` snapshot — the pick loop runs under
    the router lock and must NEVER wait on a socket there. A replica
    whose poller cannot reach it reads ``failed``, which is exactly
    the signal the router's supervision turns into a respawn (via
    :class:`RemoteReplicaSpec`).

    ``submit()`` speaks streaming ``/generate``: the response's ndjson
    lines drive a local :class:`RequestHandle` from a reader thread,
    so the router's relay (condition-variable waits on ``_tokens`` /
    ``_status``) works on it unchanged. Backpressure maps back to the
    exceptions the router already classifies: 429 →
    ``RequestRejected("queue_full")``, 503 → ``RequestRejected`` with
    the server's reason, 400 → ValueError (the capacity verdict), and
    a mid-stream ``failed:`` trailer is re-typed by message so
    page-pool exhaustion stays a request-scoped terminal and a
    preemption-budget trip stays an overload migration.
    """

    def __init__(self, base_url: str, *,
                 proc: Optional[subprocess.Popen] = None,
                 poll_interval_s: float = 0.2,
                 io_timeout_s: float = 5.0,
                 stream_timeout_s: float = 600.0,
                 admission_probe_s: float = 0.25,
                 wire_retries: int = 2,
                 retry_backoff_s: float = 0.05,
                 retry_backoff_max_s: float = 1.0,
                 max_resumes: int = 2):
        self.base_url = base_url.rstrip("/")
        self.proc = proc                  # owned subprocess (or None:
        #                                   attached — never killed)
        self.io_timeout_s = io_timeout_s
        self.stream_timeout_s = stream_timeout_s
        self.admission_probe_s = admission_probe_s
        self.poll_interval_s = poll_interval_s
        # exactly-once wire knobs: submit retries are safe because
        # every attempt carries the SAME idempotency key (a retried
        # ambiguous POST attaches to the live request server-side
        # instead of double-executing); a torn stream resumes on the
        # SAME replica from the last received token (warm KV, no
        # re-prefill) before failover replay is ever considered
        self.wire_retries = wire_retries
        self.retry_backoff_s = retry_backoff_s
        self.retry_backoff_max_s = retry_backoff_max_s
        self.max_resumes = max_resumes
        self.resumes = 0                  # mid-stream resumes served
        self.submit_retries = 0           # wire-level resubmissions
        self.integrity_rejects = 0        # KV ships the peer refused
        # testing seam: a paddle_tpu.testing.faults.NetworkFaultPlan
        # fired at the wire sites ("generate", "kv_import") — bounded
        # delay / connection drop / mid-stream half-close, so the chaos
        # suite can prove failover replay absorbs a torn wire, not just
        # a dead engine
        self.fault_plan = None
        self.queue = _RemoteQueue(self)
        self.engine = _RemoteEngine(self)
        self.slo = _RemoteSLO(self)
        self._lock = threading.Lock()
        self._next_id = 0                 # guarded-by: self._lock
        self._snapshot = {"status": "failed",
                          "error": "never polled"}
        self._snap_ts = 0.0
        self._stop = threading.Event()
        self._poller = threading.Thread(
            target=self._poll_loop, daemon=True,
            name=f"paddle_tpu-remote-poll-{self.base_url}")
        self._refresh()                   # one synchronous fetch so a
        #                                   freshly built replica is
        #                                   routable before the first
        #                                   poll tick
        self._poller.start()

    # -- /healthz snapshot ---------------------------------------------------
    def _refresh(self) -> None:
        try:
            status, body = _http_json("GET", self.base_url, "/healthz",
                                      timeout=self.io_timeout_s)
        except OSError as e:
            body = {"status": "failed", "healthy": False,
                    "error": f"unreachable: {e}"}
        else:
            if not isinstance(body, dict) or "status" not in body:
                body = {"status": "failed", "healthy": False,
                        "error": f"bad /healthz ({status})"}
        with self._lock:
            self._snapshot = body
            self._snap_ts = time.monotonic()

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            self._refresh()

    def _snap(self) -> dict:
        with self._lock:
            return self._snapshot

    # -- Server-shaped read surface ------------------------------------------
    # The router reads these UNDER ITS LOCK on every pick/poll: they
    # must serve the poller's cached snapshot only, never the network.
    # The hot-path annotations arm PT006 (tools/lint) against a live
    # round-trip sneaking back in.
    @property
    def status(self) -> str:  # lint: hot-path
        return str(self._snap().get("status", "failed"))

    def load(self) -> dict:  # lint: hot-path
        return dict(self._snap())

    def num_active(self) -> int:  # lint: hot-path
        # lint: allow-host-sync(host dict read off the cached snapshot)
        return int(self._snap().get("active_requests", 0))

    @property
    def flight_dumps(self) -> list:  # lint: hot-path
        d = self._snap().get("flight_dump")
        return [d] if d else []

    def profile(self) -> dict:
        status, body = _http_json("GET", self.base_url, "/profile",
                                  timeout=self.io_timeout_s)
        if status != 200:
            raise RuntimeError(
                f"replica {self.base_url} /profile -> {status}")
        return body

    def stats(self) -> dict:
        status, body = _http_json("GET", self.base_url, "/stats",
                                  timeout=self.io_timeout_s)
        if status != 200:
            raise RuntimeError(
                f"replica {self.base_url} /stats -> {status}")
        return body

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Poll (directly — warmup is exactly when the cache is stale)
        until the replica reports ok/draining."""
        end = (None if timeout is None
               else time.monotonic() + timeout)
        while True:
            self._refresh()
            if self.status in ("ok", "draining"):
                return True
            if end is not None and time.monotonic() >= end:
                return False
            if self.proc is not None and self.proc.poll() is not None:
                return False              # process died during warmup
            time.sleep(0.05)

    # -- streaming submit ----------------------------------------------------
    def submit(self, prompt, cfg: Optional[GenerationConfig] = None,
               priority: int = 0,
               timeout_s: Optional[float] = None,
               trace_rid: Optional[str] = None,
               tenant: Optional[str] = None) -> RequestHandle:
        """Same contract as ``Server.submit`` across the wire. The
        admission probe waits ``admission_probe_s`` for an early
        response line — a rejection (429/503/400) answers immediately
        and raises HERE, synchronously, so router backpressure keeps
        its no-failover-budget semantics; the success status line is
        DEFERRED by the server until the first token, so its absence
        within the probe means "admitted or queued" and the reader
        thread takes over."""
        cfg = cfg or GenerationConfig()
        plen = _prompt_len(prompt)
        max_len = self.engine.max_len
        if plen + cfg.max_new_tokens > max_len:
            raise ValueError(
                f"prompt({plen}) + max_new_tokens({cfg.max_new_tokens})"
                f" exceeds engine max_len({max_len})")
        ids = (prompt.tolist() if isinstance(prompt, np.ndarray)
               else [int(t) for t in prompt])
        body = {"prompt": [int(t) for t in ids], "stream": True,
                "priority": priority}
        defaults = GenerationConfig()
        for k, v in vars(cfg).items():
            # only non-default fields travel: the remote server's OWN
            # defaults (e.g. speculative opt-in) must keep applying
            if v != getattr(defaults, k, None):
                body[k] = v
        if timeout_s is not None:
            body["timeout_s"] = timeout_s
        if tenant is not None:
            body["tenant"] = tenant
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._lock:
            rid = self._next_id
            self._next_id += 1
        # the idempotency key every wire attempt of THIS submission
        # carries: the router's stable rid keeps it identical across
        # retries (so a retried ambiguous POST attaches to the live
        # request server-side instead of double-executing), and the
        # per-submit salt keeps a failover REPLAY — a new submission
        # with the same trace rid — from attaching to the zombie it
        # replaces
        idem = (f"{trace_rid if trace_rid is not None else self.base_url}"
                f"#{rid}")
        body["idem_key"] = idem

        state = {"conn": None, "closed": False}
        handle = RequestHandle(
            rid, prompt, plen, cfg, priority, deadline,
            on_cancel=lambda h: self._abort(state),
            tenant=(tenant if tenant is not None
                    else getattr(cfg, "adapter", None)))
        handle._trace_rid = (trace_rid if trace_rid is not None
                             else f"{self.base_url}:{rid}")
        handle._trace_ttft = trace_rid is None

        import http.client
        from urllib.parse import urlsplit

        u = urlsplit(self.base_url)
        attempt = 0
        while True:
            conn = http.client.HTTPConnection(u.hostname, u.port,
                                              timeout=self.io_timeout_s)
            state["conn"] = conn
            state["closed"] = False
            early = None
            try:
                if self.fault_plan is not None:
                    # network seam: a delay sleeps right here, a drop
                    # raises ConnectionResetError into the retry path
                    # below (exactly a refused/reset socket), and a
                    # half-close/corrupt spec rides in ``state`` for
                    # the reader thread to consume mid-stream
                    state["cut"] = self.fault_plan.fire("generate")
                payload = json.dumps(body).encode()
                conn.request("POST", "/generate", body=payload,
                             headers={"Content-Type":
                                      "application/json"})
                # the admission probe: readable within the window
                # means the server already answered — only rejections
                # and instant terminals do (the 200 status line waits
                # for the first token), so its absence means "admitted
                # or queued" and the reader thread takes over
                r, _, _ = select.select([conn.sock], [], [],
                                        self.admission_probe_s)
                if r:
                    early = conn.getresponse()
                    if early.status != 200:
                        raw = early.read()
                        self._close_conn(state)
                        self._raise_rejection(early.status, raw,
                                              handle)
                        return handle     # 504/500 finished the handle
            except RequestRejected:
                raise
            except ValueError:
                raise
            except OSError as e:
                # the AMBIGUOUS wire failure (the server may or may
                # not have admitted): safe to retry because the idem
                # key dedups server-side. Bounded exponential backoff,
                # and never a retry that cannot land before the
                # request's own deadline — shed those instead.
                self._close_conn(state)
                wait = min(self.retry_backoff_s * (2.0 ** attempt),
                           self.retry_backoff_max_s)
                attempt += 1
                if attempt > self.wire_retries:
                    raise RuntimeError(
                        f"replica {self.base_url} unreachable after "
                        f"{attempt} attempt(s): {e}") from e
                if (deadline is not None
                        and time.monotonic() + wait >= deadline):
                    raise RequestRejected(
                        "deadline_doomed",
                        f"replica {self.base_url}: wire retry would "
                        f"outlive the request deadline ({e})",
                        retry_after_s=None) from e
                self.submit_retries += 1
                if trace.enabled():
                    trace.event("wire.retry", rid=handle._trace_rid,
                                attempt=attempt, wait_s=wait,
                                cause=repr(e))
                time.sleep(wait)
                continue
            break
        reader = threading.Thread(
            target=self._read_stream,
            args=(state, handle, early, body, idem),
            daemon=True,
            name=f"paddle_tpu-remote-stream-{self.base_url}-{rid}")
        reader.start()
        return handle

    def _raise_rejection(self, status: int, raw: bytes,
                         handle: RequestHandle) -> None:
        """Map an early (pre-stream) HTTP error onto the submit
        contract: raise for backpressure/validation, finish the handle
        for per-request terminals."""
        try:
            body = json.loads(raw) if raw else {}
        except json.JSONDecodeError:
            body = {}
        msg = body.get("error", f"HTTP {status}")
        if status == 429:
            # carry the server's reason ("queue_full" vs the control
            # plane's "shed") and its Retry-After hint through — the
            # router's backpressure classification and a client's
            # backoff both depend on them surviving the hop
            raise RequestRejected(
                body.get("reason", "queue_full"), msg,
                retry_after_s=body.get("retry_after_s"))
        if status == 503:
            # draining/warming replicas now publish a drain-ETA /
            # warmup-estimate Retry-After too — same passthrough
            raise RequestRejected(
                body.get("reason", "degraded"), msg,
                retry_after_s=body.get("retry_after_s"))
        if status == 400:
            raise ValueError(msg)
        if status == 504:
            handle._finish(EXPIRED)
            return
        handle._finish(FAILED, RequestFailed(
            f"replica {self.base_url} -> {status}: {msg}"))

    def _abort(self, state: dict) -> None:
        """Cancel path: shear the socket. The remote handler's broken-
        pipe guard cancels the request server-side; the reader thread
        unblocks on the dead socket and finishes the handle."""
        self._close_conn(state)

    @staticmethod
    def _close_conn(state: dict) -> None:
        state["closed"] = True
        conn = state.get("conn")
        if conn is None:
            return
        try:
            if conn.sock is not None:
                conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass

    @staticmethod
    def _classify_failure(msg: str) -> BaseException:
        """Re-type a ``failed: <msg>`` stream trailer so the router's
        verdict logic keeps working across the wire: page-pool
        exhaustion is a request-scoped capacity terminal, a preemption-
        budget trip is an overload migration — everything else is a
        replica-attributed failover."""
        low = msg.lower()
        if "page pool exhausted" in low or "cannot ever hold" in low:
            return PagePoolExhausted(msg)
        if "preempt" in low and "budget" in low:
            return PreemptionBudgetExceeded(msg)
        return RequestFailed(msg)

    def _read_stream(self, state: dict, handle: RequestHandle,
                     early, body: Optional[dict] = None,
                     idem: Optional[str] = None) -> None:
        """Reader thread: relay one /generate ndjson stream into the
        local handle. Terminal mapping mirrors ``_stream_response``'s
        writer side; a torn stream (socket error, EOF without a done
        line) first tries a MID-STREAM RESUME — reconnect to the SAME
        replica with the idempotency key + ``from_token`` so the server
        reattaches the live handle and replays only the tokens we
        missed (warm KV intact, no re-prefill). Only when resumes are
        exhausted or the replica looks genuinely unhealthy does the
        tear surface as a replica-attributed failure for the router's
        failover replay — unless the tear was OUR cancel, which must
        read CANCELLED, not failover."""
        import http.client
        from urllib.parse import urlsplit

        err: Optional[BaseException] = None
        done_line = None
        resumed = 0
        while True:
            conn = state["conn"]
            err = None
            done_line = None
            try:
                if early is not None:
                    resp = early
                    early = None
                else:
                    resp = conn.getresponse()
                if resp.status != 200:
                    raw = resp.read()
                    try:
                        self._raise_rejection(resp.status, raw, handle)
                    except (RequestRejected, ValueError) as e:
                        # after the probe window these cannot raise
                        # into the caller anymore — carry them on the
                        # handle (the router relays RequestRejected ->
                        # failover, ValueError -> request-scoped
                        # terminal)
                        handle._finish(FAILED, e)
                    return
                # streaming begins: per-token gaps may be long (a cold
                # compile, a busy batch) — widen the per-recv timeout
                # from the connect/admission one to the stream one
                if conn.sock is not None:
                    conn.sock.settimeout(self.stream_timeout_s)
                first = len(handle.tokens_so_far()) == 0
                cut = state.get("cut")    # injected mid-stream tear
                relayed = 0
                while True:
                    line = resp.readline()
                    if not line:
                        break             # EOF without a done line
                    line = line.strip()
                    if not line:
                        continue
                    if (cut is not None
                            and cut.get("action") == "corrupt"
                            and cut.get("mode") == "flip"
                            and relayed >= cut["after"]):
                        # injected corruption: garble this line in
                        # flight — json.loads below tears exactly like
                        # real bit-rot would
                        line = bytes(b ^ 0xFF for b in line)
                    rec = json.loads(line)
                    if "token" in rec:
                        if first:
                            first = False
                            # admission is invisible over the wire
                            # until the first token: mark RUNNING here
                            # (engine rid is remote-private;
                            # -1 = "remote")
                            handle._mark_running(-1)
                        handle._push([int(rec["token"])])
                        relayed += 1
                        if (cut is not None
                                and cut.get("mode") != "flip"
                                and relayed >= cut["after"]):
                            # injected half-close (or truncation):
                            # walk away with the server mid-stream —
                            # no done line, so the tear enters the
                            # resume path below; server-side the
                            # broken-pipe guard parks the handle in
                            # the dedup window for the grace period
                            break
                    elif rec.get("done"):
                        done_line = rec
                        break
            except Exception as e:  # noqa: BLE001 - any tear (socket
                #   error, torn chunk framing, http.client's own
                #   internal races when the cancel path shears the
                #   socket under a blocked read) must RESOLVE the
                #   handle — an unresolved handle strands the router's
                #   relay forever
                err = e
            finally:
                self._close_conn(state)
            if handle.done:
                return
            if handle._cancel_requested:
                handle._finish(CANCELLED)
                return
            if done_line is not None:
                break
            # torn stream. Resume against the SAME replica first: the
            # server-side dedup window still holds the live handle (a
            # broken pipe with an idem key orphans, not cancels), so a
            # reconnect keyed on idem + from_token replays only the
            # missing tail against warm KV. Failover (full re-prefill
            # elsewhere) is the fallback, not the first move.
            if (idem is not None and body is not None
                    and resumed < self.max_resumes
                    and self.status in ("ok", "draining")):
                resumed += 1
                self.resumes += 1
                from_token = len(handle.tokens_so_far())
                if trace.enabled():
                    trace.event("wire.resume", rid=handle._trace_rid,
                                attempt=resumed,
                                from_token=from_token,
                                cause=repr(err) if err else "eof")
                try:
                    u = urlsplit(self.base_url)
                    conn = http.client.HTTPConnection(
                        u.hostname, u.port,
                        timeout=self.io_timeout_s)
                    state["conn"] = conn
                    state["closed"] = False
                    if self.fault_plan is not None:
                        state["cut"] = self.fault_plan.fire("generate")
                    else:
                        state["cut"] = None
                    rbody = dict(body)
                    rbody["from_token"] = from_token
                    conn.request(
                        "POST", "/generate",
                        body=json.dumps(rbody).encode(),
                        headers={"Content-Type": "application/json"})
                    continue              # next loop getresponse()s
                except OSError as e:
                    err = e
                    self._close_conn(state)
                    # fall through to the failover terminal
            handle._finish(FAILED, RequestFailed(
                f"replica {self.base_url} stream broke: "
                f"{err!r}" if err is not None else
                f"replica {self.base_url} stream ended without a "
                "done line"))
            return
        status = str(done_line.get("status", "finished"))
        if status == "finished":
            handle._finish(FINISHED)
        elif status == "cancelled":
            handle._finish(CANCELLED)
        elif status == "expired":
            handle._finish(EXPIRED)
        else:                             # "failed: <message>"
            msg = status.partition(":")[2].strip() or status
            handle._finish(FAILED, self._classify_failure(msg))

    # -- KV page handoff (disaggregated prefill/decode) ----------------------
    def export_kv_raw(self, tokens, salt: bytes = b"") -> bytes:
        """``POST /kv/export`` — the replica's resident full-block
        pages covering ``tokens``, as framed wire bytes. Kept RAW on
        purpose: the disaggregated front ships these bytes to the
        decode replica untouched (a page copy, never a conversion —
        and never a decode/re-encode hop in the middle)."""
        body = json.dumps(
            {"tokens": [int(t) for t in tokens],
             "salt": salt.hex()}).encode()
        status, raw = _http_raw("POST", self.base_url, "/kv/export",
                                body, "application/json",
                                timeout=self.stream_timeout_s)
        if status != 200:
            try:
                msg = json.loads(raw).get("error", "")
            except json.JSONDecodeError:
                msg = raw.decode("utf-8", "replace")
            raise RuntimeError(
                f"replica {self.base_url} /kv/export -> {status}: "
                f"{msg}")
        return raw

    def import_kv_raw(self, raw: bytes) -> dict:
        """``POST /kv/import`` — install framed pages into the
        replica's pool + prefix index. Idempotent: chain hashes dedup
        a replayed ship into ``{"deduped": n}``."""
        if self.fault_plan is not None:
            # network seam: delay sleeps, drop raises (surfaces as the
            # shipper's RuntimeError/OSError); a half-close truncates
            # the payload mid-ship — the server sees torn framing and
            # rejects, and the front's retry must re-ship (idempotent
            # by chain hash, so a retry after a PARTIAL install dedups)
            spec = self.fault_plan.fire("kv_import")
            if spec is not None and spec.get("action") == "half_close":
                raw = raw[:max(1, len(raw) // 2)]
            elif spec is not None and spec.get("action") == "corrupt":
                if spec.get("mode") == "truncate":
                    # torn mid-transfer but past the header: framing
                    # length no longer matches — the integrity layer
                    # must reject BEFORE any page installs
                    raw = raw[:max(5, (len(raw) * 3) // 4)]
                else:                     # "flip"
                    # single byte-flip in the array tail: framing
                    # survives, the payload digest does not — exactly
                    # the silent bit-rot the checksums exist for
                    raw = raw[:-1] + bytes([raw[-1] ^ 0xFF])
        status, out = _http_raw("POST", self.base_url, "/kv/import",
                                raw, "application/octet-stream",
                                timeout=self.stream_timeout_s)
        try:
            body = json.loads(out)
        except json.JSONDecodeError:
            body = {"error": out.decode("utf-8", "replace")}
        if status != 200:
            if body.get("reason") == "integrity":
                # typed-and-counted: the shipper distinguishes "the
                # bytes rotted (re-ship, dedup makes it idempotent)"
                # from "the replica is broken (failover)"
                self.integrity_rejects += 1
                if trace.enabled():
                    trace.event("kv.integrity_reject",
                                url=self.base_url,
                                error=str(body.get("error")))
                raise KVIntegrityError(
                    f"replica {self.base_url} /kv/import rejected: "
                    f"{body.get('error')}")
            raise RuntimeError(
                f"replica {self.base_url} /kv/import -> {status}: "
                f"{body.get('error')}")
        return body

    # -- lifecycle -----------------------------------------------------------
    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait for the replica's queued + active work to finish (the
        remote server keeps accepting — cross-process drain is an
        observation, not a command; the router drains ITSELF and this
        bounds the tail)."""
        end = (None if timeout is None
               else time.monotonic() + timeout)
        while True:
            self._refresh()
            snap = self._snap()
            if (snap.get("queue_depth", 0) == 0
                    and snap.get("active_requests", 0) == 0):
                return True
            if end is not None and time.monotonic() >= end:
                return False
            time.sleep(0.05)

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop the client (poller) and, for an OWNED process, the
        process: SIGTERM, bounded wait, SIGKILL. An attached replica
        (built from a URL) is left running — we didn't start it."""
        if drain:
            self.drain(timeout=timeout)
        self._stop.set()
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10.0)

    def close(self) -> None:
        self.shutdown(drain=False)


# ---------------------------------------------------------------------------
# spawning replica processes
# ---------------------------------------------------------------------------
_READY_MARKER = "PADDLE_TPU_REPLICA_PORT="


def spawn_replica(extra_args: Optional[List[str]] = None, *,
                  startup_timeout_s: float = 120.0,
                  env: Optional[dict] = None):
    """Start ``python -m paddle_tpu.serving.remote`` and wait for its
    ready marker. Returns ``(proc, base_url)``. The child inherits our
    environment (JAX_PLATFORMS included) and binds an ephemeral port —
    parallel test runs never collide."""
    cmd = [sys.executable, "-m", "paddle_tpu.serving.remote",
           "--port", "0"] + list(extra_args or [])
    child_env = dict(os.environ)
    if env:
        child_env.update(env)
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env=child_env, text=True)
    end = time.monotonic() + startup_timeout_s
    port = None
    while time.monotonic() < end:
        line = proc.stdout.readline()
        if not line:
            break                         # child died before readiness
        if line.startswith(_READY_MARKER):
            port = int(line[len(_READY_MARKER):].strip())
            break
    if port is None:
        rc = proc.poll()
        proc.kill()
        raise RuntimeError(
            f"replica process did not become ready within "
            f"{startup_timeout_s}s (exit={rc}, cmd={cmd})")
    return proc, f"http://127.0.0.1:{port}"


class RemoteReplicaSpec(ReplicaSpec):
    """A :class:`ReplicaSpec` whose ``build()`` produces a
    :class:`RemoteReplica` — the router's supervised restart becomes a
    process respawn (spawn mode) or a reattach (url mode). Passes the
    router's ``isinstance(spec, ReplicaSpec)`` gate by construction,
    and the rest of the seam is duck-typed."""

    def __init__(self, *, args: Optional[List[str]] = None,
                 url: Optional[str] = None,
                 startup_timeout_s: float = 120.0,
                 env: Optional[dict] = None,
                 replica_kwargs: Optional[dict] = None):
        if (args is None) == (url is None):
            raise ValueError(
                "exactly one of 'args' (spawn a replica process) or "
                "'url' (attach to a running one) is required")
        # the factory is unused (build() is overridden) but the base
        # validates it — hand it something honest about that
        super().__init__(lambda: None)
        self.args = list(args) if args is not None else None
        self.url = url
        self.startup_timeout_s = startup_timeout_s
        self.env = dict(env) if env else None
        self.replica_kwargs = dict(replica_kwargs or {})

    def build(self) -> RemoteReplica:
        if self.url is not None:
            return RemoteReplica(self.url, **self.replica_kwargs)
        proc, base_url = spawn_replica(
            self.args, startup_timeout_s=self.startup_timeout_s,
            env=self.env)
        return RemoteReplica(base_url, proc=proc,
                             **self.replica_kwargs)


# ---------------------------------------------------------------------------
# disaggregated prefill/decode front
# ---------------------------------------------------------------------------
class DisaggregatedFront:
    """Splitwise/DistServe-shaped serving over two (or more) replicas:
    the PREFILL replica runs chunked prefill to completion — budget 1,
    so the scheduler's whole admission/chunking machinery applies —
    then its finished pages (chain hashes included) ship raw to the
    DECODE replica, which continues ``prompt + [t0]`` against the warm
    prefix. Byte-identity with a monolithic engine is the bar: the
    handoff is a page copy keyed by the same chain hashes the prefix
    cache already trusts, so the decode side's lookup is exactly the
    warm-restart path PR 9 proved.

    Failover: a decode replica dying mid-stream replays
    ``prompt + tokens emitted so far`` on the prefill replica — whose
    pages are STILL RESIDENT (it prefilled them), so the replay is a
    warm continuation, not a recompute. Same causal-replay argument as
    the in-process router."""

    def __init__(self, prefill: RemoteReplica, decode: RemoteReplica,
                 *, max_failovers: int = 1,
                 max_integrity_failures: int = 3):
        self.prefill = prefill
        self.decode = decode
        self.max_failovers = max_failovers
        # after this many integrity rejects the front stops trusting
        # the wire and decodes on the prefill replica (local prefill —
        # pages never travel), rather than serving off a suspect pool
        self.max_integrity_failures = max_integrity_failures
        self.handoffs = 0                 # pages shipped (blocks)
        self.dedups = 0                   # blocks dedup'd on import
        self.failovers = 0
        self.reships = 0                  # integrity-triggered retries
        self.integrity_rejects = 0        # corrupt payloads refused

    def ship(self, prompt, salt: bytes = b"") -> dict:
        """Ship the prefill replica's pages covering ``prompt`` to the
        decode replica. Returns the import verdict
        ``{"imported", "deduped", "coverage"}``. A corrupt arrival is
        rejected whole by the decode side (nothing installed), so one
        re-ship of freshly exported bytes is safe — the chain-hash
        dedup makes a retry after any partial progress idempotent."""
        attempts = 0
        while True:
            raw = self.prefill.export_kv_raw(
                [int(t) for t in prompt], salt=salt)
            try:
                out = self.decode.import_kv_raw(raw)
            except KVIntegrityError:
                self.integrity_rejects += 1
                attempts += 1
                if attempts > 1:
                    raise
                self.reships += 1
                continue
            self.handoffs += int(out.get("imported", 0))
            self.dedups += int(out.get("deduped", 0))
            return out

    def generate(self, prompt, cfg: Optional[GenerationConfig] = None,
                 timeout_s: Optional[float] = None) -> RequestHandle:
        """One disaggregated request; returns a local handle streaming
        the combined result (t0 from prefill, the rest from decode)."""
        cfg = cfg or GenerationConfig()
        plen = _prompt_len(prompt)
        ids = [int(t) for t in (prompt.tolist()
                                if isinstance(prompt, np.ndarray)
                                else prompt)]
        handle = RequestHandle(0, np.asarray(ids, np.int32), plen,
                               cfg, 0, None)
        t = threading.Thread(
            target=self._pump, args=(handle, ids, cfg, timeout_s),
            daemon=True, name="paddle_tpu-disagg-pump")
        t.start()
        return handle

    def _pump(self, handle: RequestHandle, ids: list,
              cfg: GenerationConfig,
              timeout_s: Optional[float]) -> None:
        try:
            # phase 1: prefill to completion (budget 1 -> the first
            # token proves the full prompt prefilled and its blocks
            # registered in the prefix index)
            kw = dict(vars(cfg))
            kw["max_new_tokens"] = 1
            h1 = self.prefill.submit(ids, GenerationConfig(**kw),
                                     timeout_s=timeout_s)
            t0 = int(h1.result(timeout=self.prefill.stream_timeout_s)
                     [0])
            handle._mark_running(-1)
            handle._push([t0])
            if cfg.max_new_tokens == 1:
                handle._finish(FINISHED)
                return
            # phase 2: ship the prompt's finished pages, decode the
            # remaining budget against the warm prefix. Past the
            # integrity-failure budget the wire is suspect: skip the
            # ship and decode on the prefill replica itself (its pages
            # never travelled, so correctness is untouched — only the
            # disaggregation win is given up)
            salt = (str(cfg.adapter).encode()
                    if getattr(cfg, "adapter", None) else b"")
            emitted = [t0]
            target = self.decode
            if self.integrity_rejects >= self.max_integrity_failures:
                target = self.prefill
            else:
                try:
                    self.ship(ids, salt=salt)
                except KVIntegrityError:
                    # both the ship and its one re-ship arrived
                    # corrupt — decode locally, nothing installed
                    target = self.prefill
            failovers = 0
            while True:
                kw = dict(vars(cfg))
                kw["max_new_tokens"] = cfg.max_new_tokens - \
                    len(emitted)
                try:
                    h2 = target.submit(ids + emitted,
                                       GenerationConfig(**kw),
                                       timeout_s=timeout_s)
                    for tok in h2.stream(
                            timeout=target.stream_timeout_s):
                        emitted.append(int(tok))
                        handle._push([int(tok)])
                except (RequestFailed, RequestRejected, RuntimeError,
                        TimeoutError) as e:
                    failovers += 1
                    self.failovers += 1
                    if failovers > self.max_failovers:
                        raise
                    # decode replica died mid-stream: replay the
                    # emitted prefix on the prefill replica, whose
                    # pages never left
                    target = self.prefill
                    continue
                handle._finish(FINISHED)
                return
        except BaseException as e:  # noqa: BLE001 - client must not hang
            if not handle.done:
                handle._finish(FAILED, e)


# ---------------------------------------------------------------------------
# the replica process entrypoint
# ---------------------------------------------------------------------------
def _build_server(ns):
    """One seeded toy Server from the CLI — deterministic init, so
    every replica spawned with the same knobs holds bitwise-identical
    weights (the property greedy failover parity and the disaggregated
    byte-identity bar both ride on)."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.generation import (
        PagedContinuousBatchingEngine)
    from paddle_tpu.models import LlamaForCausalLM, llama_config
    from paddle_tpu.serving import Server

    paddle.seed(ns.model_seed)
    cfg = llama_config(ns.preset, num_hidden_layers=ns.layers)
    model = LlamaForCausalLM(cfg)
    eng = PagedContinuousBatchingEngine(
        model, max_batch=ns.max_batch, num_pages=ns.num_pages,
        page_size=ns.page_size, max_pages=ns.max_pages,
        prefill_chunk=ns.prefill_chunk,
        prefix_cache=(ns.prefix_cache == "on"),
        kv_dtype=ns.kv_dtype,
        lora_capacity=ns.adapters)
    slo_policy = None
    if ns.slo_ttft is not None or ns.slo_tpot is not None:
        from paddle_tpu.monitor.slo import SLOPolicy

        slo_policy = SLOPolicy(ttft_p99_s=ns.slo_ttft,
                               tpot_p99_s=ns.slo_tpot)
    srv = Server(eng, max_queue=ns.max_queue,
                 segment_steps=ns.segment_steps,
                 warmup=(ns.warmup == "on"),
                 slo_policy=slo_policy)
    return srv


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.remote",
        description="one out-of-process toy replica: build a seeded "
                    "Server, serve HTTP, print the bound port")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 = ephemeral (the ready marker names it)")
    p.add_argument("--preset", default="tiny")
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--model-seed", type=int, default=0)
    p.add_argument("--max-batch", type=int, default=2)
    p.add_argument("--num-pages", type=int, default=64)
    p.add_argument("--page-size", type=int, default=8)
    p.add_argument("--max-pages", type=int, default=16)
    p.add_argument("--prefill-chunk", type=int, default=None)
    p.add_argument("--prefix-cache", choices=("on", "off"),
                   default="on")
    p.add_argument("--kv-dtype", default="bf16",
                   choices=("bf16", "int8"))
    p.add_argument("--adapters", type=int, default=0)
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--segment-steps", type=int, default=4)
    p.add_argument("--warmup", choices=("on", "off"), default="off")
    p.add_argument("--slo-ttft", type=float, default=None)
    p.add_argument("--slo-tpot", type=float, default=None)
    ns = p.parse_args(argv)

    from .http import serve_http

    srv = _build_server(ns)
    srv.wait_ready()
    httpd = serve_http(srv, addr=ns.host, port=ns.port)
    port = httpd.server_address[1]
    # the ready marker the parent's spawn_replica() waits for — keep
    # it the LAST startup line and flush: the parent reads stdout
    # line-buffered
    print(f"{_READY_MARKER}{port}", flush=True)

    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not stop.wait(0.2):
        pass
    httpd.shutdown()
    srv.shutdown(drain=False, timeout=10.0)
    try:
        srv.engine.close()
    except Exception:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
