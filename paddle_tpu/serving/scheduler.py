"""Online continuous-batching scheduler: :class:`Server`.

The reference stack drives its engine from a server loop above
AnalysisPredictor; here a dedicated scheduler THREAD owns a
``ContinuousBatchingEngine`` / ``PagedContinuousBatchingEngine`` and
drives the stepwise API (``add_request`` / ``decode_segment`` /
``collect_finished``) in an Orca-style iteration loop:

    gap:   apply cancellations → advance an in-flight CHUNKED admission
           by ONE fixed-shape prefill chunk → reap expired → admit from
           the queue (capacity probed via the engine's public
           ``can_admit`` / ``free_slots`` — never by catching
           add_request's RuntimeError); prompts longer than the engine's
           ``prefill_chunk`` admit chunk-by-chunk across gaps, so a long
           prompt never monopolizes the gap and running requests' TPOT
           stays flat
    step:  one jitted decode segment over every occupied slot
    drain: stream new tokens to handles, finish retired requests

Admission happens only in the inter-segment gap, so a transiently full
pool defers work instead of failing it; cancellation retires the slot in
the same gap, so the pool is reclaimed, never leaked. Backpressure is
the bounded queue: ``submit`` on a full queue raises
:class:`~paddle_tpu.serving.queue.QueueFull` (the HTTP layer's 429).

Thread model: the engine is touched by the scheduler thread ONLY (jax
tracing included). ``submit``/``cancel``/``drain``/``shutdown`` are
thread-safe entry points that communicate through the queue, handle
flags, and a wake event.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from .. import monitor
from ..inference.generation import GenerationConfig, _prompt_len
from .queue import (CANCELLED, EXPIRED, FAILED, FINISHED, QueueFull,
                    RequestHandle, RequestQueue, RequestRejected)

__all__ = ["Server"]


class Server:
    """Thread-driven online server over a continuous-batching engine.

    Usage::

        eng = PagedContinuousBatchingEngine(model, max_batch=4,
                                            num_pages=64, page_size=16,
                                            max_pages=32)
        srv = Server(eng, max_queue=64, segment_steps=8)
        h = srv.submit(prompt_ids, GenerationConfig(max_new_tokens=64))
        for tok in h.stream():      # tokens arrive segment by segment
            ...
        srv.shutdown()

    ``submit`` rejects (raises) when the queue is full or the server is
    draining — the reject-with-reason backpressure contract; a request
    whose prompt can NEVER fit the engine fails fast with ValueError.
    ``drain()`` stops admission of new submissions and waits for
    in-flight + queued work to finish; ``shutdown()`` optionally drains,
    then cancels whatever remains and stops the thread.

    ``warmup=True`` pre-compiles every serving-path program
    (``engine.warmup``: all prefill buckets, the chunked-prefill
    program, the decode segment) in the scheduler thread before the
    loop starts — no user request ever pays an XLA compile.
    ``status``/``/healthz`` report ``warming`` until done (submissions
    queue meanwhile); gate traffic on :meth:`wait_ready`. When the
    engine was built with ``prefill_chunk``, prompts longer than the
    chunk admit one fixed-shape chunk per inter-segment gap with decode
    segments interleaved — a long prompt never stalls running requests.
    """

    def __init__(self, engine, max_queue: int = 64,
                 segment_steps: int = 8,
                 idle_wait_s: float = 0.02, start: bool = True,
                 warmup: bool = False):
        self.engine = engine
        self.segment_steps = segment_steps
        self.idle_wait_s = idle_wait_s
        self.warmup = warmup
        self.queue = RequestQueue(max_queue)
        # per-server label: concurrent servers (multi-model processes)
        # publish their serving metrics side by side
        self.monitor_server = monitor.instance_label("server")
        self._wake = threading.Event()
        self._idle_cv = threading.Condition()
        self._lock = threading.Lock()     # submit/lifecycle flags
        self._next_id = 0
        self._active = {}                 # engine rid -> RequestHandle
        self._admitting = False           # True between queue pop and
        #                                   _active insert (drain must
        #                                   not miss that window)
        self._adm = None                  # in-flight chunked admission:
        #                                   (engine admission, handle) —
        #                                   advanced ONE chunk per gap
        self._draining = False
        self._stopping = False
        self._fatal: Optional[BaseException] = None
        self._ready = threading.Event()   # warmup done (set immediately
        #                                   when warmup=False)
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"paddle_tpu-serving-{self.monitor_server}")
        if start:
            self._thread.start()

    # -- client surface ------------------------------------------------------
    def submit(self, prompt, cfg: Optional[GenerationConfig] = None,
               priority: int = 0,
               timeout_s: Optional[float] = None) -> RequestHandle:
        """Enqueue one request; returns its :class:`RequestHandle`.

        ``cfg`` is the request's OWN GenerationConfig (validated at
        construction — malformed configs never reach a shared decode
        segment); ``priority`` orders admission (lower first);
        ``timeout_s`` sets an admission deadline — a request still
        queued when it passes is EXPIRED, never admitted.

        Raises :class:`RequestRejected` (reason ``queue_full`` /
        ``draining`` / ``shutdown``) for backpressure, ValueError for a
        prompt that could never fit the engine."""
        cfg = cfg or GenerationConfig()
        plen = _prompt_len(prompt)
        if plen + cfg.max_new_tokens > self.engine.max_len:
            raise ValueError(
                f"prompt({plen}) + max_new_tokens({cfg.max_new_tokens}) "
                f"exceeds engine max_len({self.engine.max_len})")
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        # the put happens under the SAME lock as the stopping check:
        # otherwise a submit racing shutdown() could enqueue after the
        # scheduler's final queue drain and strand the handle QUEUED
        # forever (no thread left to ever finish it)
        with self._lock:
            if self._stopping or self._stopped.is_set():
                # covers clean shutdown AND a scheduler that died on an
                # exception — either way nobody will ever pop the queue
                self._count("rejected_shutdown")
                raise RequestRejected(
                    "shutdown",
                    "server is shut down"
                    + (f" (scheduler died: {self._fatal!r})"
                       if self._fatal is not None else ""))
            if self._draining:
                self._count("rejected_draining")
                raise RequestRejected(
                    "draining",
                    "server is draining; not accepting new requests")
            handle = RequestHandle(self._next_id, prompt, plen, cfg,
                                   priority, deadline,
                                   on_cancel=self._on_cancel)
            self._next_id += 1
            try:
                self.queue.put(handle)
            except QueueFull:
                self._count("rejected_queue_full")
                raise
        self._count("queued")
        self._depth_gauge()
        self._wake.set()
        return handle

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop accepting NEW submissions, let queued + in-flight
        requests run to completion. Returns True when everything
        finished (False on timeout; the server keeps draining)."""
        with self._lock:
            self._draining = True
        self._wake.set()
        with self._idle_cv:
            return self._idle_cv.wait_for(
                lambda: (self.queue.depth == 0 and not self._active
                         and not self._admitting and self._adm is None)
                or self._stopped.is_set(), timeout)

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop the scheduler. ``drain=True`` finishes outstanding work
        first (bounded by ``timeout``); whatever remains afterwards —
        or everything, with ``drain=False`` — is cancelled BY THE
        SCHEDULER THREAD on its way out (the engine is never touched
        from the caller's thread — a segment still in flight, e.g. a
        long first compile, finishes before cleanup runs)."""
        t0 = time.monotonic()
        if drain:
            self.drain(timeout)
        with self._lock:
            self._stopping = True
            self._draining = True
        self._wake.set()
        # ``timeout`` bounds the WHOLE call: the stop-wait gets what the
        # drain left over, not a second full helping
        if timeout is None:
            self._stopped.wait(60.0)
        else:
            self._stopped.wait(max(0.0, timeout
                                   - (time.monotonic() - t0)))
        try:
            self._queue_depth_gauge().remove(server=self.monitor_server)
            self._active_gauge().remove(server=self.monitor_server)
        except Exception:
            pass

    def close(self) -> None:
        self.shutdown(drain=False)

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def num_active(self) -> int:
        return len(self._active)

    # -- monitor helpers -----------------------------------------------------
    @staticmethod
    def _requests_counter():
        return monitor.counter(
            "paddle_tpu_serving_requests_total",
            "serving-layer requests by lifecycle event "
            "(queued/completed/cancelled/expired/failed/rejected_*)",
            ("server", "event"))

    @staticmethod
    def _queue_depth_gauge():
        return monitor.gauge(
            "paddle_tpu_serving_queue_depth",
            "requests waiting for admission, per server", ("server",))

    @staticmethod
    def _active_gauge():
        return monitor.gauge(
            "paddle_tpu_serving_active_requests",
            "requests currently occupying engine slots, per server",
            ("server",))

    @staticmethod
    def _ttft_hist():
        return monitor.histogram(
            "paddle_tpu_serving_ttft_seconds",
            "time to first token: submit() to the first generated "
            "token reaching the handle", ("server",))

    @staticmethod
    def _tpot_hist():
        return monitor.histogram(
            "paddle_tpu_serving_tpot_seconds",
            "time per output token after the first (decode cadence): "
            "(finish - first_token) / (n_tokens - 1)", ("server",))

    def _count(self, event: str) -> None:
        if monitor.enabled():
            self._requests_counter().labels(
                server=self.monitor_server, event=event).inc()

    def _depth_gauge(self) -> None:
        if monitor.enabled():
            self._queue_depth_gauge().labels(
                server=self.monitor_server).set(self.queue.depth)
            self._active_gauge().labels(
                server=self.monitor_server).set(len(self._active))

    # -- scheduler loop (single thread) --------------------------------------
    def _on_cancel(self, handle: RequestHandle) -> None:
        self._wake.set()

    def _loop(self) -> None:
        err: Optional[BaseException] = None
        try:
            if self.warmup:
                # pre-compile every serving-path program IN the engine-
                # owning thread, off the request path: no user request
                # ever pays an XLA compile. /healthz reports "warming"
                # until this finishes (submissions queue meanwhile).
                self.engine.warmup(self.segment_steps)
            self._ready.set()
            while True:
                with self._lock:
                    stopping = self._stopping
                if stopping:
                    break
                self._gap()
                if self._active or self._adm is not None:
                    # with only a chunked admission in flight the
                    # segment is a fast no-op and the loop spins
                    # straight back into _gap for the next chunk
                    self.engine.decode_segment(self.segment_steps)
                    self._collect()
                else:
                    with self._idle_cv:
                        self._idle_cv.notify_all()
                    self._wake.wait(self.idle_wait_s)
                    self._wake.clear()
        except BaseException as e:     # noqa: BLE001 - must not hang clients
            err = e
        finally:
            # terminal cleanup runs HERE, in the engine-owning thread:
            # a dead loop must never strand handles in a non-terminal
            # state (clients block in result()/stream() forever) or
            # leave drain() waiting on a condition nobody will signal.
            self._finalize(err)
            # unblock wait_ready() even when WARMUP itself died — the
            # fatal status is already recorded, and `status` reports
            # failed/stopped before it ever consults _ready
            self._ready.set()
            self._stopped.set()
            with self._idle_cv:
                self._idle_cv.notify_all()

    @property
    def status(self) -> str:
        """``warming`` (pre-compiling, not ready for traffic — requests
        still queue) / ``ok`` / ``draining`` / ``failed`` (scheduler
        died on an exception) / ``stopped`` — what ``/healthz``
        reports."""
        if self._fatal is not None:
            return "failed"
        if self._stopped.is_set():
            return "stopped"
        if not self._ready.is_set():
            return "warming"
        return "draining" if self.draining else "ok"

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until warmup finished (immediately True when
        ``warmup=False``). Also returns when the scheduler DIED during
        warmup — check :attr:`status` (``"failed"``) before serving."""
        return self._ready.wait(timeout)

    def _finalize(self, err: Optional[BaseException]) -> None:
        fail = err is not None
        with self._lock:
            # close the submit door BEFORE draining (on the crash path
            # _stopping is still False here — without this a racing
            # submit could enqueue after the final drain and strand its
            # handle QUEUED forever)
            self._stopping = True
            self._fatal = err
        wrapped = (RuntimeError(f"serving scheduler died: {err!r}")
                   if fail else None)
        if self._adm is not None:
            adm, h = self._adm
            self._adm = None
            if not fail:
                try:    # engine coherent on a clean stop — reclaim
                    self.engine.abort_admit(adm)
                except Exception:
                    pass
            h._finish(FAILED if fail else CANCELLED, wrapped)
            self._count("failed" if fail else "cancelled")
        for h in self.queue.drain_all():
            h._finish(FAILED if fail else CANCELLED, wrapped)
            self._count("failed" if fail else "cancelled")
        for rid, h in list(self._active.items()):
            if not fail:
                # engine state is coherent on a clean stop — reclaim
                try:
                    self.engine.cancel_request(rid)
                except Exception:
                    pass
            h._finish(FAILED if fail else CANCELLED, wrapped)
            self._count("failed" if fail else "cancelled")
        self._active.clear()

    def _gap(self) -> None:
        """The inter-segment gap: cancellations first (they free
        capacity), then ONE chunk of any in-flight chunked admission
        (bounded gap work — decode segments run between chunks), then
        expiry reaping, then admission while the engine's capacity
        probe allows."""
        # 1. cancellations of RUNNING requests retire their slots
        for rid, h in list(self._active.items()):
            if h._cancel_requested:
                toks = self.engine.cancel_request(rid)
                del self._active[rid]
                if toks is not None:
                    self._push_delta(h, list(toks[h._n_pushed:]))
                h._finish(CANCELLED)
                self._count("cancelled")
        # 1b. advance the in-flight chunked admission by ONE fixed-shape
        #     chunk (or abandon it if its client cancelled / its
        #     admission deadline passed — chunked admission spans many
        #     gaps, so queue.reap alone no longer covers the whole wait
        #     for admission): admission work per gap stays bounded no
        #     matter how long the prompt
        if self._adm is not None:
            adm, h = self._adm
            expired = (h.deadline is not None
                       and time.monotonic() >= h.deadline)
            if h._cancel_requested or expired:
                self._adm = None
                self.engine.abort_admit(adm)
                h._finish(CANCELLED if h._cancel_requested else EXPIRED)
                self._count("cancelled" if h._cancel_requested
                            else "expired")
            else:
                try:
                    finished = self.engine.admit_chunk(adm)
                except Exception as e:
                    self._adm = None
                    h._finish(FAILED, e)
                    self._count("failed")
                else:
                    if finished:
                        self._adm = None
                        h._mark_running(adm.rid)
                        self._active[adm.rid] = h
                        toks = self.engine.partial_tokens(adm.rid)
                        if toks is not None:
                            self._push_delta(h, toks)
        # 2. cancelled/expired queue entries never admit
        for h in self.queue.reap(time.monotonic()):
            if h._cancel_requested:
                h._finish(CANCELLED)
                self._count("cancelled")
            else:
                h._finish(EXPIRED)
                self._count("expired")
        # 3. admission: probe, never catch — deferral is the scheduler
        #    path, add_request raising is the programmer-error path.
        #    _admitting covers the whole pop→_active window (set BEFORE
        #    the pop): a timed drain() must never see "queue empty, no
        #    actives" while a request is mid-admission (prefill can be
        #    seconds on a first compile).
        self._admitting = True
        chunk = getattr(self.engine, "prefill_chunk", None)

        def admittable(h) -> bool:
            if not self.engine.can_admit(h.prompt_len, h.cfg):
                return False
            if (chunk is not None and h.prompt_len > chunk
                    and self._adm is not None):
                # one chunked admission at a time: a second long prompt
                # defers until the in-flight one completes (its slot and
                # pages are already claimed, so capacity stays honest)
                return False
            return True

        try:
            while True:
                h = self.queue.pop_if(admittable)
                if h is None:
                    # head (if any) does not fit RIGHT NOW. With the
                    # engine completely idle it can never fit — fail it
                    # loudly instead of wedging the queue forever. The
                    # pop re-checks the probe under the queue lock: a
                    # racing submit may have put a NEW, admittable head
                    # in front, which must not be the one failed.
                    if (self.queue.depth and not self._active
                            and self.engine.free_slots()
                            == self.engine.max_batch):
                        bad = self.queue.pop_if(
                            lambda h: not self.engine.can_admit(
                                h.prompt_len, h.cfg))
                        if bad is not None:
                            bad._finish(FAILED, RuntimeError(
                                f"request {bad.id} (prompt_len="
                                f"{bad.prompt_len}, max_new_tokens="
                                f"{bad.cfg.max_new_tokens}) can never "
                                "be admitted: engine capacity (page "
                                "pool / max_len) is too small even "
                                "when idle"))
                            self._count("failed")
                        continue
                    break
                if chunk is not None and h.prompt_len > chunk:
                    # long prompt: claim capacity now, prefill one
                    # fixed-shape chunk per gap (decode segments run in
                    # between) instead of one monopolizing prefill
                    try:
                        adm = self.engine.begin_admit(h.prompt, h.cfg)
                    except Exception as e:  # pragma: no cover - skew
                        h._finish(FAILED, e)
                        self._count("failed")
                        continue
                    self._adm = (adm, h)
                    continue
                try:
                    rid = self.engine.add_request(h.prompt, h.cfg)
                except Exception as e:  # pragma: no cover - probe skew
                    h._finish(FAILED, e)
                    self._count("failed")
                    continue
                h._mark_running(rid)
                self._active[rid] = h
                # admission prefill already sampled the first token:
                # push it now — the TTFT edge for the handle's stream
                toks = self.engine.partial_tokens(rid)
                if toks is not None:
                    self._push_delta(h, toks)
        finally:
            self._admitting = False
        self._depth_gauge()

    def _push_delta(self, h: RequestHandle, toks) -> None:
        """Push newly generated tokens (scheduler thread only);
        ``_n_pushed`` keeps each gap's copy O(delta), and the first
        push is the TTFT observation."""
        h._n_pushed += len(toks)
        if h._push(toks) and monitor.enabled():
            self._ttft_hist().labels(server=self.monitor_server).observe(
                h.first_token_ts - h.submit_ts)

    def _collect(self) -> None:
        """Post-segment: finish retired requests, stream deltas for the
        still-running ones."""
        for rid, seq in self.engine.collect_finished().items():
            h = self._active.pop(rid, None)
            if h is None:      # foreign request (user drove the engine)
                continue
            self._push_delta(h, list(seq[h._n_pushed:]))
            h._finish(FINISHED)
            self._count("completed")
            if monitor.enabled():
                n = len(seq)
                if h.first_token_ts is not None and n > 1:
                    self._tpot_hist().labels(
                        server=self.monitor_server).observe(
                        (h.finish_ts - h.first_token_ts) / (n - 1))
        for rid, h in list(self._active.items()):
            delta = self.engine.partial_tokens(rid, h._n_pushed)
            if delta:
                self._push_delta(h, delta)
        self._depth_gauge()
