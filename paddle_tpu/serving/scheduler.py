"""Online continuous-batching scheduler: :class:`Server`.

The reference stack drives its engine from a server loop above
AnalysisPredictor; here a dedicated scheduler THREAD owns a
``ContinuousBatchingEngine`` / ``PagedContinuousBatchingEngine`` and
drives the stepwise API (``add_request`` / ``decode_segment`` /
``collect_finished``) in an Orca-style iteration loop:

    gap:   apply adapter admin (hot LoRA load/unload) → apply
           cancellations → advance an in-flight CHUNKED admission
           by ONE fixed-shape prefill chunk → reap expired → re-admit
           REPLAYS surviving an engine restart → admit from the queue
           (capacity probed via the engine's public ``can_admit`` /
           ``free_slots`` — never by catching add_request's
           RuntimeError); prompts longer than the engine's
           ``prefill_chunk`` admit chunk-by-chunk across gaps, so a
           long prompt never monopolizes the gap and running requests'
           TPOT stays flat
    step:  one jitted decode segment over every occupied slot
    drain: stream new tokens to handles, finish retired requests

Admission happens only in the inter-segment gap, so a transiently full
pool defers work instead of failing it; cancellation retires the slot in
the same gap, so the pool is reclaimed, never leaked. Backpressure is
the bounded queue: ``submit`` on a full queue raises
:class:`~paddle_tpu.serving.queue.QueueFull` (the HTTP layer's 429).

FAULT ISOLATION (the blast-radius contract — at serving scale faults
are routine inputs, not exceptional shutdowns):

- a REQUEST-scoped fault (malformed prompt the engine chokes on, a
  prefill error — :func:`~paddle_tpu.inference.generation.classify_fault`)
  finishes ONLY that handle as FAILED with its cause; the engine's
  admission abort guards already reclaimed the slot and pages, and the
  loop keeps serving everyone else;
- an ENGINE-scoped fault (a device error inside ``decode_segment``)
  triggers SUPERVISED RECOVERY: exponential backoff, then
  ``engine.reset_state()`` rebuilds device state (compiled programs
  kept), and every in-flight request REPLAYS — re-prefilling
  ``prompt + tokens emitted so far`` through the same bucketed/chunked
  admission machinery, continuing exactly where it left off (bitwise
  for greedy requests; sampled requests continue on a fresh noise
  stream). Restarts are bounded by ``max_restarts`` (server lifetime)
  and per-request replays by ``max_replays``; past either bound the
  fatal ``_finalize`` path fails what remains, loudly;
- a STALL (a wedged step that can't announce itself) is caught by the
  watchdog thread: ``stall_timeout_s`` without a loop heartbeat flips
  ``status``/``/healthz`` to ``degraded`` (503) until the loop beats
  again;
- KV MEMORY PRESSURE (paged engine, ``admission_mode="optimistic"``)
  is a managed degradation mode, not a fault: each gap ends by growing
  every live slot's page mapping for the coming segment, preempting
  victims (lowest priority, then youngest — never the oldest
  survivor) when the pool is dry; victims replay through normal
  admission with their generated tokens intact, bounded per request
  by ``max_preemptions``. ``pressure()``/``/healthz`` expose
  occupancy, parked-waiting counts, and the preemption total so
  operators can tell pressure degradation apart from faults.

Thread model: the engine is touched by the scheduler thread ONLY (jax
tracing included) — recovery and replay run there too. The watchdog
thread only reads the heartbeat and flips flags.
``submit``/``cancel``/``drain``/``shutdown`` are thread-safe entry
points that communicate through the queue, handle flags, and a wake
event.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Optional

import numpy as np

from .. import monitor
from .. import tracing as trace
from ..monitor import ledger as _ledger
from ..monitor import slo as _slo
from ..inference.generation import (ADMISSION_MODES, GenerationConfig,
                                    PagePoolExhausted, SPEC_MODES,
                                    _prompt_ids, _prompt_len,
                                    classify_fault)
from .control import RUNG_ACTIONS, ControlPlane, ControlPolicy
from .queue import (CANCELLED, EXPIRED, FAILED, FINISHED, QueueFull,
                    RequestHandle, RequestQueue, RequestRejected)

__all__ = ["Server", "PreemptionBudgetExceeded"]


class PreemptionBudgetExceeded(RuntimeError):
    """A request was preempted under KV memory pressure more than its
    ``max_preemptions`` budget allows: it is THRASHING (admitted,
    preempted, replayed, preempted again...) and is failed with this
    typed cause instead of cycling through the pool forever. Clients
    see it as the ``RequestFailed.__cause__`` of ``result()``."""


class _EngineFaultSignal(Exception):
    """Internal: an engine-scoped fault crossing from a guarded seam to
    the loop's recovery handler (never escapes the Server). ``handle``
    rides along when a specific request's admission triggered it — that
    request joins the replay set instead of being stranded."""

    def __init__(self, site: str, cause: BaseException,
                 handle: Optional[RequestHandle] = None):
        super().__init__(f"engine fault at {site}: {cause!r}")
        self.site = site
        self.cause = cause
        self.handle = handle


class Server:
    """Thread-driven online server over a continuous-batching engine.

    Usage::

        eng = PagedContinuousBatchingEngine(model, max_batch=4,
                                            num_pages=64, page_size=16,
                                            max_pages=32)
        srv = Server(eng, max_queue=64, segment_steps=8)
        h = srv.submit(prompt_ids, GenerationConfig(max_new_tokens=64))
        for tok in h.stream():      # tokens arrive segment by segment
            ...
        srv.shutdown()

    ``submit`` rejects (raises) when the queue is full or the server is
    draining/degraded — the reject-with-reason backpressure contract; a
    request whose prompt can NEVER fit the engine fails fast with
    ValueError. ``drain()`` stops admission of new submissions and
    waits for in-flight + queued work to finish; ``shutdown()``
    optionally drains, then cancels whatever remains and stops the
    thread.

    ``warmup=True`` pre-compiles every serving-path program
    (``engine.warmup``: all prefill buckets, the chunked-prefill
    program, the decode segment) in the scheduler thread before the
    loop starts — no user request ever pays an XLA compile.
    ``status``/``/healthz`` report ``warming`` until done (submissions
    queue meanwhile); gate traffic on :meth:`wait_ready`. When the
    engine was built with ``prefill_chunk``, prompts longer than the
    chunk admit one fixed-shape chunk per inter-segment gap with decode
    segments interleaved — a long prompt never stalls running requests.

    Fault-isolation knobs:

    - ``max_restarts`` — supervised engine restarts the server will
      attempt over its LIFETIME before an engine-scoped fault falls
      through to the fatal path (like a supervisor's restart
      intensity);
    - ``restart_backoff_s`` / ``restart_backoff_max_s`` — exponential
      backoff before restart *n* sleeps
      ``min(restart_backoff_s * 2**(n-1), restart_backoff_max_s)``;
    - ``max_replays`` — engine restarts any ONE request may survive;
      past it the request fails with the fault as its cause;
    - ``stall_timeout_s`` — arm the stall watchdog (None = off): a
      scheduler step exceeding it flips status to ``degraded`` until
      the loop beats again. Without ``warmup=True`` the first request's
      XLA compiles run inside a step — set the timeout above worst-case
      compile time, or warm up. The watchdog never arms during warmup.

    Memory-pressure knobs (paged engine in ``optimistic`` admission
    mode — see :class:`PagedContinuousBatchingEngine`):

    - ``admission_mode`` — convenience mirror of the paged engine's
      knob (``"reserved"``/``"optimistic"``; None leaves the engine's
      own setting). In optimistic mode admission claims only the
      prompt's pages + one page of headroom and slots GROW per gap;
      when growth cannot be satisfied the scheduler PREEMPTS victims —
      lowest priority first, then youngest, never the oldest surviving
      request (guaranteed forward progress) — reclaiming their slot
      and pages and parking the handle on the replay list, so it
      re-admits through the normal bucketed/chunked prefill with its
      generated tokens intact (greedy preempt-resume is
      bitwise-identical to an unpreempted run);
    - ``max_preemptions`` — memory-pressure preemptions any ONE
      request may absorb; past it the request FAILS with
      :class:`PreemptionBudgetExceeded` as its cause instead of
      thrashing through the pool forever;
    - ``kv_dtype`` — convenience mirror of the paged engine's KV
      storage dtype (``"bf16"``/``"int8"``; None leaves the engine's
      own setting). ``"int8"`` stores KV pages int8 with per-page
      scales: half the decode read bytes, ~2x the pages at fixed HBM
      — which directly lifts the optimistic-admission concurrency
      ceiling — at a BOUNDED (not bitwise) numerics contract; the
      swap rebuilds the pools, so it is idle-engine-only;
    - ``age_after_s`` — queue priority aging (None = strict static
      priority): a waiting request's effective priority improves one
      level per ``age_after_s`` seconds queued, so low-priority work
      cannot starve forever under sustained high-priority load.

    Speculative-decoding knobs (engines built with ``draft_k > 0`` —
    see :class:`ContinuousBatchingEngine`):

    - ``draft_k`` — convenience mirror of the engine's draft-window
      knob (None leaves the engine's own setting); set it before
      ``warmup`` so the widened verify program pre-compiles;
    - ``spec_mode`` — mirror of the engine's speculative execution
      mode (``"host"`` | ``"device"``; None leaves the engine's own
      setting): ``"device"`` drafts from the per-slot device history
      ring and fuses the whole propose→verify→accept segment into one
      compiled program — zero per-verify-step host syncs, tokens
      stream per SEGMENT instead of per step;
    - ``speculative`` — True makes speculation the server DEFAULT for
      every eligible request (greedy; sampled requests always decode
      plain). Individual requests opt in/out via
      ``GenerationConfig.speculative`` regardless.

    SLO & goodput (``paddle_tpu.monitor.slo``, gated like every
    monitor seam on ``FLAGS_enable_monitor``):

    - the server always carries an :class:`SLOTracker` (``self.slo``)
      digesting TTFT / TPOT / queue-wait / e2e per (metric, tenant)
      into mergeable fixed-log-bucket digests, plus per-tenant token
      and KV-page-second cost counters — tenant defaults to the
      request's LoRA adapter (PR 13), base traffic aggregates under
      ``"-"``;
    - ``slo_policy`` (an :class:`~paddle_tpu.monitor.slo.SLOPolicy`)
      additionally scores every service-terminal request: **goodput**
      (fraction meeting the thresholds; FAILED requests miss by
      definition, cancelled/expired are client verdicts and don't
      count) and fast/slow **burn-rate** windows per tenant;
    - read it via ``load()``'s ``slo`` block (``/healthz``),
      :meth:`stats` (the ``GET /stats`` shape), or the fleet Router's
      ``GET /stats``, which MERGES replica digests for exact fleet
      percentiles.

    Tracing & flight recorder (``paddle_tpu.tracing``, enabled via
    ``FLAGS_enable_trace``): every lifecycle seam the scheduler drives
    records a structured event keyed by the request — queue
    enqueue/dequeue/expire, the admission span (with the prefill
    bucket) and each chunked-prefill chunk, gap and pressure-relief
    spans, decode segments (with the live request set), preempt /
    replay / restart / backoff, and fault classification. Read one
    request's ordered timeline via ``handle.timeline()`` /
    :meth:`request_timeline` / HTTP ``GET /trace?rid=``. The scheduler
    AUTO-DUMPS the trace ring (the flight recorder) on engine-scoped
    faults, watchdog ``degraded`` flips, and preemption storms
    (>= ``STORM_PREEMPTS`` preemptions within ``STORM_WINDOW_S``
    seconds); dump paths surface in :meth:`fault_stats` under
    ``flight_dumps`` and as ``/healthz``'s ``flight_dump`` field.
    """

    # preemption-storm flight-dump trigger: this many preemptions
    # inside the sliding window dumps the ring once (re-arming after a
    # full window) — thrashing under KV pressure is a postmortem-worthy
    # state even though no single preemption is a fault
    STORM_PREEMPTS = 8
    STORM_WINDOW_S = 5.0
    # shed-storm flight-dump trigger (control plane): this many shed
    # 429s inside the sliding window dumps the ring once per window —
    # each 429 is the control plane working as intended, but a reject
    # STORM is exactly the overload postmortem the black box exists for
    SHED_STORM = 8
    SHED_STORM_WINDOW_S = 5.0

    def __init__(self, engine, max_queue: int = 64,
                 segment_steps: int = 8,
                 idle_wait_s: float = 0.02, start: bool = True,
                 warmup: bool = False,
                 max_restarts: int = 3,
                 restart_backoff_s: float = 0.05,
                 restart_backoff_max_s: float = 2.0,
                 max_replays: int = 2,
                 stall_timeout_s: Optional[float] = None,
                 max_preemptions: int = 5,
                 admission_mode: Optional[str] = None,
                 age_after_s: Optional[float] = None,
                 draft_k: Optional[int] = None,
                 spec_mode: Optional[str] = None,
                 speculative: bool = False,
                 kv_dtype: Optional[str] = None,
                 tenant_quotas=None,
                 slo_policy=None,
                 control_policy=None):
        if stall_timeout_s is not None and stall_timeout_s <= 0:
            raise ValueError(
                f"stall_timeout_s must be > 0 or None, got "
                f"{stall_timeout_s!r}")
        if stall_timeout_s is not None \
                and stall_timeout_s < 2 * idle_wait_s:
            # an IDLE loop only beats every idle_wait_s (the _wake
            # wait), so a timeout at/below that cadence would flap a
            # perfectly healthy idle server into degraded
            raise ValueError(
                f"stall_timeout_s({stall_timeout_s}) must be >= twice "
                f"idle_wait_s({idle_wait_s}) — the idle loop only "
                "beats once per idle_wait_s")
        if max_restarts < 0 or max_replays < 0:
            raise ValueError("max_restarts/max_replays must be >= 0")
        if max_preemptions < 0:
            raise ValueError("max_preemptions must be >= 0")
        if admission_mode is not None:
            # convenience mirror of the paged engine's knob: set it
            # here (before the scheduler thread starts) instead of at
            # engine construction. getattr/setattr so a FaultyEngine
            # proxy routes to the wrapped engine.
            if admission_mode not in ADMISSION_MODES:
                raise ValueError(
                    f"admission_mode must be one of {ADMISSION_MODES}, "
                    f"got {admission_mode!r}")
            if getattr(engine, "admission_mode", None) is None:
                raise ValueError(
                    "admission_mode needs a paged engine "
                    "(PagedContinuousBatchingEngine)")
            if getattr(engine, "_slot_req", None):
                raise ValueError(
                    "admission_mode can only be set on an idle engine")
            engine.admission_mode = admission_mode
        if kv_dtype is not None:
            # convenience mirror of the paged engine's KV storage
            # dtype (see PagedContinuousBatchingEngine kv_dtype):
            # routed through the engine's idle-only set_kv_dtype hook
            # — a dtype swap REBUILDS the pools, so a plain attribute
            # set would silently serve bf16 pools labeled int8.
            # Set before the scheduler thread starts so warmup
            # pre-compiles the dtype's program variants.
            from ..quantization.kv import KV_DTYPES

            if kv_dtype not in KV_DTYPES:
                raise ValueError(
                    f"kv_dtype must be one of {KV_DTYPES}, got "
                    f"{kv_dtype!r}")
            set_fn = getattr(engine, "set_kv_dtype", None)
            if set_fn is None:
                raise ValueError(
                    "kv_dtype needs a paged engine "
                    "(PagedContinuousBatchingEngine)")
            if getattr(engine, "_slot_req", None):
                raise ValueError(
                    "kv_dtype can only be set on an idle engine")
            set_fn(kv_dtype)
        if draft_k is not None:
            # convenience mirror of the engine's speculative-decoding
            # knob (see ContinuousBatchingEngine draft_k): set before
            # the scheduler thread starts so warmup pre-compiles the
            # widened verify program. getattr/setattr so a FaultyEngine
            # proxy routes to the wrapped engine.
            if (isinstance(draft_k, bool) or not isinstance(draft_k, int)
                    or not 0 <= draft_k <= 256):
                raise ValueError(
                    f"draft_k must be an int in [0, 256], got "
                    f"{draft_k!r}")
            if getattr(engine, "draft_k", None) is None:
                raise ValueError(
                    "draft_k needs a continuous-batching engine")
            if getattr(engine, "_slot_req", None):
                raise ValueError(
                    "draft_k can only be set on an idle engine")
            engine.draft_k = draft_k
        if spec_mode is not None:
            # convenience mirror of the engine's speculative execution
            # mode (see ContinuousBatchingEngine spec_mode): "device"
            # fuses propose→verify→accept into one compiled segment —
            # drafts come from the per-slot device history ring, and
            # the scheduler's gap no longer drives per-step host
            # proposals for speculating slots. Set before the
            # scheduler thread starts so warmup pre-compiles the
            # mode's program (the fused segment needs segment_steps,
            # which warmup passes).
            if spec_mode not in SPEC_MODES:
                raise ValueError(
                    f"spec_mode must be one of {SPEC_MODES}, got "
                    f"{spec_mode!r}")
            if getattr(engine, "spec_mode", None) is None:
                raise ValueError(
                    "spec_mode needs a continuous-batching engine")
            if getattr(engine, "_slot_req", None):
                raise ValueError(
                    "spec_mode can only be set on an idle engine")
            engine.spec_mode = spec_mode
        if speculative and not getattr(engine, "draft_k", 0):
            raise ValueError(
                "speculative=True needs an engine built with "
                "draft_k > 0 (or pass Server(draft_k=...))")
        # speculative=True makes speculation the server DEFAULT: every
        # eligible (greedy, not explicitly opted) request decodes
        # speculatively — the per-request GenerationConfig.speculative
        # flag still opts individual requests in on a False server
        self.speculative = bool(speculative)
        # per-tenant admission quotas (None = off): an int caps every
        # tenant's concurrently ADMITTED requests uniformly; a dict
        # caps the named tenants (others unlimited). A tenant over its
        # quota DEFERS in the queue — tenants behind it still admit
        # (RequestQueue.pop_admittable skips quota-deferred entries,
        # never capacity-blocked ones) — so one noisy fine-tune cannot
        # monopolize the engine's slots or starve its neighbours.
        if tenant_quotas is not None:
            if isinstance(tenant_quotas, bool) or not (
                    isinstance(tenant_quotas, int)
                    or isinstance(tenant_quotas, dict)):
                raise ValueError(
                    f"tenant_quotas must be None, a positive int, or a "
                    f"dict {{tenant: cap}}, got {tenant_quotas!r}")
            caps = (tenant_quotas.values()
                    if isinstance(tenant_quotas, dict)
                    else (tenant_quotas,))
            if any(isinstance(c, bool) or not isinstance(c, int)
                   or c < 1 for c in caps):
                raise ValueError(
                    f"tenant quota caps must be ints >= 1, got "
                    f"{tenant_quotas!r}")
        self.tenant_quotas = tenant_quotas
        if slo_policy is not None and not isinstance(slo_policy,
                                                     _slo.SLOPolicy):
            raise ValueError(
                f"slo_policy must be a monitor.slo.SLOPolicy or None, "
                f"got {slo_policy!r}")
        # SLO/goodput tracker (paddle_tpu.monitor.slo): mergeable
        # per-(metric, tenant) latency digests + per-tenant cost
        # accounting, always constructed (a cheap host object) but
        # only FED while FLAGS_enable_monitor is on — the disabled
        # serving path pays one bool branch per seam, nothing else.
        # slo_policy additionally scores each finished request into
        # goodput + fast/slow burn-rate windows. Read via load()'s
        # ``slo`` block, stats(), and the fleet Router's GET /stats
        # (which MERGES these digests — exact fleet percentiles).
        self.slo = _slo.SLOTracker(policy=slo_policy)
        if control_policy is not None and not isinstance(
                control_policy, ControlPolicy):
            raise ValueError(
                f"control_policy must be a serving.control.ControlPolicy "
                f"or None, got {control_policy!r}")
        # SLO-driven overload control plane (serving.control): consumes
        # the tracker's burn windows + queue occupancy in the gap and
        # actuates burn-rate shedding (429 + Retry-After at submit),
        # the brownout ladder, and quota tightening. Entirely host-side
        # — engaging any rung compiles nothing. None = no control.
        self.control = (None if control_policy is None
                        else ControlPlane(
                            control_policy,
                            fast_window_s=(slo_policy.fast_window_s
                                           if slo_policy is not None
                                           else 60.0)))
        self.engine = engine
        self.segment_steps = segment_steps
        self.idle_wait_s = idle_wait_s
        self.warmup = warmup
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        self.restart_backoff_max_s = restart_backoff_max_s
        self.max_replays = max_replays
        self.max_preemptions = max_preemptions
        self.stall_timeout_s = stall_timeout_s
        self.queue = RequestQueue(max_queue, age_after_s=age_after_s)
        # per-server label: concurrent servers (multi-model processes)
        # publish their serving metrics side by side
        self.monitor_server = monitor.instance_label("server")
        self._wake = threading.Event()
        self._idle_cv = threading.Condition()
        self._lock = threading.Lock()     # submit/lifecycle flags
        self._next_id = 0                 # guarded-by: self._lock
        self._active = {}                 # engine rid -> RequestHandle
        self._admitting = False           # True for the whole inter-
        #                                   segment gap and recovery:
        #                                   handles pass through locals
        #                                   there, and drain must not
        #                                   miss those windows
        self._adm = None                  # in-flight chunked admission:
        #                                   (engine admission, handle) —
        #                                   advanced ONE chunk per gap
        self._replay = []                 # handles surviving an engine
        #                                   restart, awaiting
        #                                   re-admission (replay)
        self._faulted = False             # True while a handle rides an
        #                                   in-flight fault signal
        #                                   (between its seam and
        #                                   _recover) — drain must not
        #                                   report done in that window
        self._restarts = 0
        # guarded-by: self._lock
        self._flight_dumps = []           # flight-recorder dump paths
        #                                   (fault_stats / healthz
        #                                   read them)
        self._preempt_ts = []             # recent preemption stamps for
        #                                   the storm trigger (scheduler
        #                                   thread only)
        self._last_storm_dump = -1e18
        self._shed_lock = threading.Lock()
        self._shed_ts = []                # guarded-by: self._shed_lock
        #                                   recent shed-429 stamps for
        #                                   the shed-storm trigger
        #                                   (submit runs on CLIENT
        #                                   threads, unlike preemptions)
        self._last_shed_dump = -1e18      # guarded-by: self._shed_lock
        self._admin_ops = []              # guarded-by: self._lock
        #                                   pending adapter load/unload
        #                                   requests, applied by the
        #                                   scheduler thread in the
        #                                   inter-segment gap
        self._fault_counts = {}           # guarded-by: self._lock
        #                                   (kind, site) -> n, host-side
        #                                   (monitor-independent; see
        #                                   fault_stats())
        self._recovery_s = []             # guarded-by: self._lock
        self._waiting_on_pages = 0        # preempted handles parked on
        #                                   the replay list right now
        #                                   (pressure surface; scheduler
        #                                   thread writes, healthz reads
        #                                   — an int store is atomic)
        self._degraded_reason: Optional[str] = None   # guarded-by: self._lock
        self._stall_flag = False          # guarded-by: self._lock
        #                                   (degraded BY the watchdog)
        self._beat = time.monotonic()     # loop heartbeat the watchdog
        #                                   reads (float store: atomic)
        self._draining = False            # guarded-by: self._lock
        self._stopping = False            # guarded-by: self._lock
        self._fatal: Optional[BaseException] = None   # guarded-by: self._lock
        self._ready = threading.Event()   # warmup done (set immediately
        #                                   when warmup=False)
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"paddle_tpu-serving-{self.monitor_server}")
        self._watchdog = None
        if stall_timeout_s is not None:
            self._watchdog = threading.Thread(
                target=self._watch, daemon=True,
                name=f"paddle_tpu-serving-watchdog-"
                     f"{self.monitor_server}")
        if start:
            self._thread.start()

    # -- client surface ------------------------------------------------------
    def submit(self, prompt, cfg: Optional[GenerationConfig] = None,
               priority: int = 0,
               timeout_s: Optional[float] = None,
               trace_rid: Optional[str] = None,
               tenant: Optional[str] = None) -> RequestHandle:
        """Enqueue one request; returns its :class:`RequestHandle`.

        ``cfg`` is the request's OWN GenerationConfig (validated at
        construction — malformed configs never reach a shared decode
        segment); ``priority`` orders admission (lower first);
        ``timeout_s`` sets an admission deadline — a request still
        queued when it passes is EXPIRED, never admitted.
        ``trace_rid`` overrides the trace key this request's lifecycle
        events are recorded under (default
        ``<server_label>:<handle id>``) — the replica router passes its
        OWN stable key here so one request's timeline stays whole
        across a failover to a different replica. ``tenant`` names the
        request's quota bucket (``Server(tenant_quotas=...)``); it
        defaults to the request's LoRA ``cfg.adapter`` — the fine-tune
        IS the tenant in multi-tenant serving — and ``None`` (no
        adapter either) leaves the request un-quotaed.

        Raises :class:`RequestRejected` (reason ``queue_full`` /
        ``draining`` / ``degraded`` / ``shutdown`` / ``shed`` — the
        last with ``retry_after_s`` set from the tenant's burn window,
        Server(control_policy=...) only) for backpressure,
        ValueError for a prompt that could never fit the engine. A
        degraded server (stalled step, mid-recovery) rejects
        IMMEDIATELY with the reason instead of queueing into a server
        that may never drain."""
        cfg = cfg or GenerationConfig()
        if (self.speculative and not cfg.do_sample
                and not cfg.speculative):
            # server-level default opt-in: copy, never mutate the
            # caller's config (vars() so future fields carry over)
            kw = dict(vars(cfg))
            kw["speculative"] = True
            cfg = GenerationConfig(**kw)
        plen = _prompt_len(prompt)
        if plen + cfg.max_new_tokens > self.engine.max_len:
            raise ValueError(
                f"prompt({plen}) + max_new_tokens({cfg.max_new_tokens}) "
                f"exceeds engine max_len({self.engine.max_len})")
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        eff_tenant = (tenant if tenant is not None
                      else getattr(cfg, "adapter", None))
        if self.control is not None and eff_tenant is not None:
            # burn-rate admission control: a tenant whose fast-burn
            # window fired is shed AT THE DOOR for the rest of the
            # window — its queued entries are deprioritized (not these;
            # see _control_tick) and new arrivals bounce with a
            # Retry-After telling the client when the window clears.
            # Checked OUTSIDE self._lock: the storm trigger below may
            # write a flight dump, which takes self._lock itself.
            ra = self.control.shed_check(eff_tenant, time.monotonic())
            if ra is not None:
                self._count("rejected_shed")
                self._note_shed(eff_tenant, "burn_rate")
                raise RequestRejected(
                    "shed",
                    f"tenant {eff_tenant!r} exceeded its SLO error "
                    f"budget (fast-burn window); retry in {ra:.1f}s",
                    retry_after_s=ra)
        # the put happens under the SAME lock as the stopping check:
        # otherwise a submit racing shutdown() could enqueue after the
        # scheduler's final queue drain and strand the handle QUEUED
        # forever (no thread left to ever finish it)
        with self._lock:
            if self._stopping or self._stopped.is_set():
                # covers clean shutdown AND a scheduler that died on an
                # exception — either way nobody will ever pop the queue
                self._count("rejected_shutdown")
                raise RequestRejected(
                    "shutdown",
                    "server is shut down"
                    + (f" (scheduler died: {self._fatal!r})"
                       if self._fatal is not None else ""))
            if self._draining:
                self._count("rejected_draining")
                # drain ETA: queued + active work at a rough
                # quarter-second-per-request decode pace — the same
                # honest-hint contract as the 429 Retry-After paths,
                # so a client (or the router) waits out the drain
                # instead of hammering a server that told it when
                eta = 0.5 + 0.25 * (self.queue.depth
                                    + len(self._active))
                raise RequestRejected(
                    "draining",
                    "server is draining; not accepting new requests",
                    retry_after_s=eta)
            if self._degraded_reason is not None:
                self._count("rejected_degraded")
                raise RequestRejected(
                    "degraded",
                    f"server is degraded ({self._degraded_reason}); "
                    "not accepting new requests")
            handle = RequestHandle(self._next_id, prompt, plen, cfg,
                                   priority, deadline,
                                   on_cancel=self._on_cancel,
                                   tenant=eff_tenant)
            # the trace key pairs the server label with the request id:
            # concurrent servers in one process restart their ids at 0,
            # and the process-wide ring must not merge their timelines
            # (a router-supplied key replaces it so a failover's second
            # replica keeps appending to the SAME timeline)
            handle._trace_rid = (trace_rid if trace_rid is not None
                                 else f"{self.monitor_server}:{handle.id}")
            # under a router-supplied rid this handle is replica-inner
            # plumbing: the ROUTER handle owns the one first_token
            # (TTFT) edge — a failover resubmit's first push here is
            # mid-stream, not a TTFT edge
            handle._trace_ttft = trace_rid is None
            self._next_id += 1
            try:
                self.queue.put(handle)
            except QueueFull:
                self._count("rejected_queue_full")
                raise
        self._count("queued")
        if trace.enabled():
            attrs = {}
            if getattr(cfg, "adapter", None) is not None:
                attrs["adapter"] = cfg.adapter
            trace.event("queue.enqueue", rid=handle._trace_rid,
                        plen=plen, priority=priority,
                        depth=self.queue.depth, **attrs)
        self._depth_gauge()
        self._wake.set()
        return handle

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop accepting NEW submissions, let queued + in-flight
        requests run to completion (replays included). Returns True
        when everything finished (False on timeout; the server keeps
        draining)."""
        with self._lock:
            self._draining = True
        self._wake.set()
        with self._idle_cv:
            return self._idle_cv.wait_for(
                lambda: (self.queue.depth == 0 and not self._active
                         and not self._admitting and self._adm is None
                         and not self._replay and not self._faulted)
                or self._stopped.is_set(), timeout)

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop the scheduler. ``drain=True`` finishes outstanding work
        first (bounded by ``timeout``); whatever remains afterwards —
        or everything, with ``drain=False`` — is cancelled BY THE
        SCHEDULER THREAD on its way out (the engine is never touched
        from the caller's thread — a segment still in flight, e.g. a
        long first compile, finishes before cleanup runs)."""
        t0 = time.monotonic()
        if not self._thread.is_alive() and not self._stopped.is_set():
            # never-started server (``start=False``): no loop will ever
            # set _stopped — don't sit out the stop-wait below. (A
            # FINISHED loop sets _stopped in its finally before the
            # thread dies, so this cannot mask a real exit.)
            self._stopped.set()
        if drain:
            self.drain(timeout)
        with self._lock:
            self._stopping = True
            self._draining = True
        self._wake.set()
        # ``timeout`` bounds the WHOLE call: the stop-wait gets what the
        # drain left over, not a second full helping
        if timeout is None:
            self._stopped.wait(60.0)
        else:
            self._stopped.wait(max(0.0, timeout
                                   - (time.monotonic() - t0)))
        if not self._stopped.is_set():
            # the loop is still wedged (the stall scenario): leave the
            # per-server series alone — a live scheduler/watchdog tick
            # would just re-create anything removed here, and the
            # series still describe a real, running (if sick) server
            return
        if self._watchdog is not None and self._watchdog.is_alive():
            # a watchdog tick racing the removal below would re-create
            # the degraded/fault series; it exits within one poll
            # period of _stopped
            self._watchdog.join(timeout=2.0)
        try:
            self._queue_depth_gauge().remove(server=self.monitor_server)
            self._active_gauge().remove(server=self.monitor_server)
        except Exception:
            pass
        # per-server series retire with the server (the event/site
        # dimensions are open-ended; a dropped server must not export
        # its last degraded flag — or its lifecycle counters and
        # latency histograms — forever). The requests/ttft/tpot
        # families were the leak tests/test_monitor.py's
        # TestSeriesRetirement caught when it generalized the PR 3-7
        # hand-fixes into one regression.
        for name in ("paddle_tpu_serving_faults_total",
                     "paddle_tpu_serving_restarts_total",
                     "paddle_tpu_serving_degraded",
                     "paddle_tpu_serving_recovery_seconds",
                     "paddle_tpu_serving_kv_pressure",
                     "paddle_tpu_serving_requests_total",
                     "paddle_tpu_serving_ttft_seconds",
                     "paddle_tpu_serving_tpot_seconds",
                     # SLO/goodput + per-tenant cost families (PR 15):
                     # tenant is an open label dimension, retired by
                     # the server label alone
                     "paddle_tpu_serving_goodput",
                     "paddle_tpu_serving_slo_misses_total",
                     "paddle_tpu_serving_tenant_tokens_total",
                     "paddle_tpu_serving_tenant_kv_page_seconds_total",
                     # overload control plane (PR 19): sheds carry an
                     # open tenant/reason dimension, the rung gauge
                     # would export a stale brownout forever
                     "paddle_tpu_serving_sheds_total",
                     "paddle_tpu_serving_brownout_rung"):
            try:
                monitor.remove_series(name, server=self.monitor_server)
            except Exception:
                pass

    def close(self) -> None:
        self.shutdown(drain=False)

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def num_active(self) -> int:
        return len(self._active)

    @property
    def restarts(self) -> int:
        """Supervised engine restarts so far (lifetime count the
        ``max_restarts`` bound applies to)."""
        return self._restarts

    def fault_stats(self) -> dict:
        """Host-side fault/recovery accounting, monitor-independent
        (the chaos bench reads this even with the monitor off):
        ``{"faults": {(kind, site): n}, "restarts": n,
        "recovery_s": [per-restart wall seconds],
        "degraded": reason-or-None,
        "flight_dumps": [flight-recorder dump paths]}`` (dumps are
        written on engine-scoped faults, watchdog ``degraded`` flips,
        and preemption storms — empty unless ``FLAGS_enable_trace`` was
        on when the trigger fired)."""
        with self._lock:
            return {"faults": dict(self._fault_counts),
                    "restarts": self._restarts,
                    "recovery_s": list(self._recovery_s),
                    "degraded": self._degraded_reason,
                    "flight_dumps": list(self._flight_dumps)}

    @property
    def flight_dumps(self):
        """Flight-recorder dump paths written so far (newest last)."""
        with self._lock:
            return list(self._flight_dumps)

    # -- multi-tenant LoRA admin (thread-safe; applied in the gap) -----------
    def load_adapter(self, name: str, params: dict, alpha=None,
                     timeout: Optional[float] = 30.0) -> int:
        """Hot-load a LoRA adapter into the engine's device bank;
        returns its bank index. Thread-safe: the request is queued and
        APPLIED BY THE SCHEDULER THREAD in the next inter-segment gap
        (the engine is never touched from the caller's thread), then
        the result — or the engine's ValidationError — propagates back
        here. Running requests are untouched; post-``warmup`` a load
        pays zero compiles. See ``engine.load_adapter`` for the
        ``params`` format."""
        self._require_adapters()
        return self._admin_op("load", (name, params, alpha), timeout)

    def unload_adapter(self, name: str,
                       timeout: Optional[float] = 30.0) -> bool:
        """Hot-unload an adapter. Returns True when its index freed
        immediately, False when live requests still decode under it —
        the unload DEFERS (new submissions naming it fail at admission;
        the index frees when the last one retires). Same marshalling
        as :meth:`load_adapter`."""
        self._require_adapters()
        return self._admin_op("unload", (name,), timeout)

    def _require_adapters(self) -> None:
        if getattr(self.engine, "adapters", None) is None:
            raise RuntimeError(
                "engine built without lora_capacity; pass "
                "lora_capacity=K at engine construction")

    # -- KV-page handoff admin (thread-safe; applied in the gap) -------------
    def export_kv(self, tokens, salt: bytes = b"",
                  timeout: Optional[float] = 30.0) -> dict:
        """Export the resident cached KV pages covering ``tokens``'
        longest full-block prefix (``engine.export_kv_pages`` payload).
        Thread-safe: marshalled to the scheduler thread's inter-segment
        gap like adapter admin — the pools are donated by device
        writes, so no other thread may ever read them. The read half of
        a disaggregated prefill->decode handoff (``POST /kv/export``)."""
        self._require_kv_handoff()
        return self._admin_op("kv_export", (tokens, salt), timeout)

    def import_kv(self, payload: dict,
                  timeout: Optional[float] = 30.0) -> dict:
        """Install an exported KV-page payload into this engine's pools
        and prefix index (``engine.import_kv_pages``): chain-hash
        verified, idempotent on replay (already-resident blocks dedup).
        Same gap marshalling as :meth:`export_kv`. The write half of
        the handoff (``POST /kv/import``)."""
        self._require_kv_handoff()
        return self._admin_op("kv_import", (payload,), timeout)

    def _require_kv_handoff(self) -> None:
        if (getattr(self.engine, "export_kv_pages", None) is None
                or not getattr(self.engine, "prefix_cache", False)):
            raise RuntimeError(
                "KV-page handoff needs a paged engine with "
                "prefix_cache=True (the content index is what makes "
                "the handoff idempotent)")

    def _admin_op(self, op: str, args, timeout):
        evt = threading.Event()
        box: dict = {}
        entry = (op, args, evt, box)
        with self._lock:
            if self._stopping or self._stopped.is_set():
                raise RequestRejected(
                    "shutdown", "server is shut down; admin ops no "
                    "longer apply")
            self._admin_ops.append(entry)
        self._wake.set()
        if not evt.wait(timeout):
            # a timed-out op must not apply LATER with nobody waiting
            # (the caller was told it failed — a silent late apply
            # would make its retry fail "already loaded"): withdraw it
            # if the scheduler has not picked it up yet
            with self._lock:
                try:
                    self._admin_ops.remove(entry)
                    withdrawn = True
                except ValueError:
                    withdrawn = False   # mid-apply: result imminent
            if withdrawn:
                raise TimeoutError(
                    f"admin op {op} not applied within {timeout}s "
                    "(withdrawn; is the scheduler wedged?)")
            # the scheduler already owns it — give the in-flight apply
            # a short grace so the caller gets the REAL verdict
            if not evt.wait(5.0):
                raise TimeoutError(
                    f"admin op {op} still applying after {timeout}s")
        if "error" in box:
            raise box["error"]
        return box["result"]

    _ADMIN_DISPATCH = {"load": "load_adapter",
                       "unload": "unload_adapter",
                       "kv_export": "export_kv_pages",
                       "kv_import": "import_kv_pages"}

    def _apply_admin(self) -> None:
        """Apply pending admin requests — adapter load/unload and
        KV-page export/import — on the scheduler thread in the
        inter-segment gap (the only place the registry or the donated
        pools may be touched). A failed op reports its error to the
        waiting caller; the engine and every running request are
        unharmed (the bank swap is all-or-nothing, and a rejected
        import adopts nothing)."""
        with self._lock:
            ops, self._admin_ops = self._admin_ops, []
        for op, args, evt, box in ops:
            try:
                box["result"] = getattr(
                    self.engine, self._ADMIN_DISPATCH[op])(*args)
            except Exception as e:
                box["error"] = e
            finally:
                evt.set()

    def request_timeline(self, request_id: int):
        """Ordered trace-event timeline for one of THIS server's
        requests by its public id (what ``/generate`` returned as
        ``request_id``) — the ``GET /trace?rid=`` surface. Same
        contract as ``RequestHandle.timeline()``: needs
        ``FLAGS_enable_trace`` on while the request ran, may be partial
        for old requests (bounded ring)."""
        return trace.timeline(f"{self.monitor_server}:{request_id}")

    def _flight_dump(self, reason: str):
        """Write a flight-recorder dump (no-op while tracing is off —
        no black box was recording) and remember its path for
        ``fault_stats``/healthz. Never raises: the dump is postmortem
        evidence, and failing to write it must not worsen the fault
        being recorded."""
        if not trace.enabled():
            return None
        try:
            path = trace.dump(reason)
        except Exception:
            return None
        if path is not None:
            with self._lock:
                self._flight_dumps.append(path)
        return path

    def load(self) -> dict:  # lint: hot-path
        """ONE lock-light, host-side load/health snapshot — the single
        source both ``/healthz`` and the replica router's least-loaded
        selection consume (no HTTP hop, no device sync):

        ``{"status", "healthy", "server", "queue_depth",
        "active_requests", "restarts", "free_slots", "active_slots",
        "max_batch"[, "free_pages", "total_pages", "occupancy"]
        [, "pressure"][, "slo"][, "control"][, "flight_dump"]}``

        ``healthy`` is the HTTP readiness verdict (``status`` in
        ``ok``/``draining`` — what ``/healthz`` turns into 200 vs 503).
        Every field is host bookkeeping: the queue and status locks are
        held only for single reads/writes, never across engine work, so
        this NEVER blocks behind a slow (or wedged) scheduler step —
        the property that lets a router keep routing around a sick
        replica while its watchdog is still counting down."""
        status = self.status
        snap = {
            "status": status,
            "healthy": status in ("ok", "draining"),
            "server": self.monitor_server,
            "queue_depth": self.queue.depth,
            # len() of a dict the scheduler thread mutates is a single
            # atomic read — no lock, no torn state
            "active_requests": len(self._active),
            "restarts": self._restarts,
        }
        eload = getattr(self.engine, "load", None)
        if eload is not None:
            snap.update(eload())
        else:   # minimal engines: keep the probe surface alive
            snap["free_slots"] = self.engine.free_slots()
        p = self.pressure()
        if p is not None:
            snap["pressure"] = p
        if monitor.enabled():
            # SLO/goodput block (host dict walks only — the tracker's
            # lock is held per read, never across engine work): policy,
            # per-tenant goodput + fast/slow burn + token/KV-page-
            # second cost, headline ttft/tpot p50/p99 per tenant.
            # Absent while nothing was recorded or the monitor is off.
            s = self.slo.snapshot()
            if s is not None:
                snap["slo"] = s
        if _ledger.enabled():
            # compact program-ledger block: top programs by total
            # dispatch seconds (host dict walk; full table on /profile)
            prof = self.profile(top_k=5)
            if prof["programs"]:
                snap["profile"] = {
                    "programs": len(prof["programs"]),
                    "total_seconds": prof["total_seconds"],
                    "top": [{k: prof["programs"][pid].get(k)
                             for k in ("program", "total_seconds",
                                       "dispatches", "mfu", "bound")}
                            for pid in prof["top"]],
                }
        if self.control is not None:
            # overload-control block (host dict walk under the plane's
            # own lock): active brownout rung + its action name, per-
            # tenant shed counts by reason, currently-shed tenants —
            # the /healthz surface ISSUE 19's satellite asks for
            snap["control"] = self.control.snapshot()
        with self._lock:
            if self._flight_dumps:
                snap["flight_dump"] = self._flight_dumps[-1]
        return snap

    def profile(self, top_k: Optional[int] = None) -> dict:
        """This server's program-ledger shard — the per-program roofline
        table ``GET /profile`` serves and the fleet Router merge-exacts
        across replicas: ``{"programs": {pid: cost/compiles/digest/
        MFU/bound}, "peaks", "top", "total_seconds"}``. Scoped to the
        programs this server's ENGINE owns (plus ownerless process-wide
        programs when the engine exposes no monitor label). Empty when
        ``FLAGS_enable_ledger`` is off."""
        own = getattr(self.engine, "_monitor_engine", None)
        prof = _ledger.profile(
            owners=[own] if own else None, top_k=top_k)
        prof["server"] = self.monitor_server
        return prof

    def stats(self) -> dict:
        """Single-server SLO/goodput rollup — the same record shape
        the fleet Router serves on ``GET /stats`` (built through the
        SAME :func:`paddle_tpu.monitor.slo.fleet_rollup` merge path,
        as a 1-shard fleet), so single-server and fleet tooling read
        one format: ``{"server", "policy", "window_s", "tenants":
        {tenant: goodput/burn/cost}, "metrics": {metric: {tenant:
        count/p50/p90/p99, "*": exact all-tenant merge}}}``."""
        out = _slo.fleet_rollup([self.slo.digests_dict()])
        out["server"] = self.monitor_server
        return out

    def pressure(self):
        """KV memory-pressure snapshot (None for a dense engine):
        ``{"admission_mode", "occupancy", "free_pages",
        "waiting_on_pages", "preemptions"}`` — what ``/healthz``
        reports so an operator can tell "degraded by memory pressure"
        (occupancy near 1.0, preemptions climbing, requests parked
        waiting on pages) apart from the stall/fault ``degraded``
        reason. With the prefix cache on the dict also carries
        ``{"prefix_cache": True, "cached_pages", "shared_pages",
        "prefix_hits", "prefix_lookups", "prefix_tokens_saved"}``
        (parked pages are reclaimable capacity, not occupancy).
        Host-side and monitor-independent, like
        :meth:`fault_stats`."""
        alloc = getattr(self.engine, "alloc", None)
        if alloc is None:
            return None
        out = {
            "admission_mode": getattr(self.engine, "admission_mode",
                                      "reserved"),
            # storage dtype travels WITH the page numbers: at fixed
            # HBM an int8 pool holds ~2x the pages, so occupancy /
            # free_pages are only comparable dtype-attached
            "kv_dtype": getattr(alloc, "kv_dtype", "bf16"),
            "occupancy": round(alloc.occupancy, 4),
            "free_pages": alloc.free_pages,
            "waiting_on_pages": self._waiting_on_pages,
            "preemptions": alloc.preemptions,
        }
        if getattr(alloc, "kv_dtype", "bf16") == "int8":
            out["kv_quant_bytes_saved"] = alloc.quant_bytes_saved
        if getattr(alloc, "prefix_cache", False):
            # prefix-cache surface: parked pages are reclaimable
            # capacity (free + cached = what admission can claim),
            # shared counts the refcount>1 multiplier, hits/saved are
            # lifetime totals
            out.update({
                "prefix_cache": True,
                "cached_pages": alloc.cached_pages,
                "shared_pages": alloc.shared_pages,
                "prefix_hits": alloc.prefix_hits,
                "prefix_lookups": alloc.prefix_lookups,
                "prefix_tokens_saved": alloc.prefix_tokens_saved,
            })
        return out

    # -- monitor helpers -----------------------------------------------------
    @staticmethod
    def _requests_counter():
        return monitor.counter(
            "paddle_tpu_serving_requests_total",
            "serving-layer requests by lifecycle event "
            "(queued/completed/cancelled/expired/failed/preempted/"
            "rejected_*)",
            ("server", "event"))

    @staticmethod
    def _queue_depth_gauge():
        return monitor.gauge(
            "paddle_tpu_serving_queue_depth",
            "requests waiting for admission, per server", ("server",))

    @staticmethod
    def _active_gauge():
        return monitor.gauge(
            "paddle_tpu_serving_active_requests",
            "requests currently occupying engine slots, per server",
            ("server",))

    @staticmethod
    def _ttft_hist():
        return monitor.histogram(
            "paddle_tpu_serving_ttft_seconds",
            "time to first token: submit() to the first generated "
            "token reaching the handle", ("server",))

    @staticmethod
    def _tpot_hist():
        return monitor.histogram(
            "paddle_tpu_serving_tpot_seconds",
            "time per output token after the first (decode cadence): "
            "(finish - first_token) / (n_tokens - 1)", ("server",))

    @staticmethod
    def _faults_counter():
        return monitor.counter(
            "paddle_tpu_serving_faults_total",
            "serving-path faults by blast-radius kind "
            "(request/engine/stall) and detection site",
            ("server", "kind", "site"))

    @staticmethod
    def _restarts_counter():
        return monitor.counter(
            "paddle_tpu_serving_restarts_total",
            "supervised engine restarts: device state rebuilt, "
            "in-flight requests replayed", ("server",))

    @staticmethod
    def _degraded_gauge():
        return monitor.gauge(
            "paddle_tpu_serving_degraded",
            "1 while the server is degraded (stalled step or "
            "mid-recovery), else 0", ("server",))

    @staticmethod
    def _recovery_hist():
        return monitor.histogram(
            "paddle_tpu_serving_recovery_seconds",
            "engine recovery wall time: fault caught -> backoff + "
            "state rebuilt + in-flight requests requeued for replay",
            ("server",))

    @staticmethod
    def _pressure_gauge():
        return monitor.gauge(
            "paddle_tpu_serving_kv_pressure",
            "requests preempted under KV memory pressure and parked "
            "on the replay list, waiting for pages, per server",
            ("server",))

    @staticmethod
    def _goodput_gauge():
        return monitor.gauge(
            "paddle_tpu_serving_goodput",
            "lifetime fraction of service-terminal requests meeting "
            "the server's SLOPolicy, per tenant (finished+failed; "
            "cancelled/expired excluded)", ("server", "tenant"))

    @staticmethod
    def _slo_miss_counter():
        return monitor.counter(
            "paddle_tpu_serving_slo_misses_total",
            "requests missing the SLO by dimension "
            "(ttft/tpot/e2e thresholds, or 'failed' for requests the "
            "service never delivered)", ("server", "tenant", "slo"))

    @staticmethod
    def _tenant_tokens_counter():
        return monitor.counter(
            "paddle_tpu_serving_tenant_tokens_total",
            "generated tokens per tenant (tenant defaults to the LoRA "
            "adapter name; '-' aggregates base traffic) — the compute "
            "half of per-tenant cost accounting",
            ("server", "tenant"))

    @staticmethod
    def _tenant_kv_counter():
        return monitor.counter(
            "paddle_tpu_serving_tenant_kv_page_seconds_total",
            "KV page-seconds held per tenant (trapezoid of the host "
            "page count over admit->finish; no device sync) — the "
            "memory half of per-tenant cost accounting",
            ("server", "tenant"))

    @staticmethod
    def _sheds_counter():
        return monitor.counter(
            "paddle_tpu_serving_sheds_total",
            "burn-rate shed rejections by tenant and reason — the "
            "control plane's 429-with-Retry-After path "
            "(Server(control_policy=...))",
            ("server", "tenant", "reason"))

    @staticmethod
    def _rung_gauge():
        return monitor.gauge(
            "paddle_tpu_serving_brownout_rung",
            "active brownout-ladder rung (0 = disengaged; order: "
            "quota_tighten, max_new_cap, spec_off, prefix_pause — see "
            "serving.control.RUNG_ACTIONS)", ("server",))

    def _count(self, event: str) -> None:
        if monitor.enabled():
            self._requests_counter().labels(
                server=self.monitor_server, event=event).inc()

    def _note_shed(self, tenant: str, reason: str) -> None:
        """Count + trace one shed rejection (runs on the SUBMITTING
        client thread) and feed the shed-storm flight trigger: each
        429 is the control plane working as intended, but a reject
        STORM is the overload postmortem the PR-8 black box exists
        for. Same sliding-window + re-arm-only-on-written-dump
        discipline as the preemption-storm trigger — the dump fires
        at most once per SHED_STORM_WINDOW_S even under concurrent
        submits (decision and re-arm share self._shed_lock)."""
        total = self.control.note_shed(tenant, reason)
        if monitor.enabled():
            self._sheds_counter().labels(
                server=self.monitor_server, tenant=tenant,
                reason=reason).inc()
        if trace.enabled():
            trace.event("control.shed", tenant=tenant, reason=reason,
                        total=total, server=self.monitor_server)
        now = time.monotonic()
        # lock order: self._shed_lock -> self._lock (via _flight_dump);
        # nothing takes them in the other order
        with self._shed_lock:
            self._shed_ts.append(now)
            cut = now - self.SHED_STORM_WINDOW_S
            while self._shed_ts and self._shed_ts[0] < cut:
                self._shed_ts.pop(0)
            if (len(self._shed_ts) >= self.SHED_STORM
                    and now - self._last_shed_dump
                    > self.SHED_STORM_WINDOW_S):
                if trace.enabled():
                    trace.event("control.shed_storm",
                                count=len(self._shed_ts),
                                window_s=self.SHED_STORM_WINDOW_S)
                if self._flight_dump("shed_storm") is not None:
                    self._last_shed_dump = now

    def _kv_page_seconds(self, h: RequestHandle, n_tokens: int) -> float:
        """Approximate KV page-seconds this request held (paged engine
        only): trapezoid of the host-side page count — pages grow
        roughly linearly from ceil(prompt/page_size) at admission to
        ceil((prompt+generated)/page_size) at retirement — times the
        admit->finish wall time. Pure host arithmetic (token counts
        the scheduler already tracks), no allocator walk, no device
        sync; pages released while preempted are slightly
        over-counted, which is the conservative direction for a cost
        meter."""
        ps = getattr(self.engine, "page_size", None)
        if not ps or h.admit_ts is None or h.finish_ts is None:
            return 0.0
        p0 = math.ceil(h.prompt_len / ps)
        p1 = math.ceil((h.prompt_len + n_tokens) / ps)
        return (p0 + p1) / 2.0 * max(h.finish_ts - h.admit_ts, 0.0)

    def _slo_finish(self, h: RequestHandle, n_tokens: int) -> None:
        """Score one FINISHED request into the SLO tracker and the
        per-tenant cost/goodput series (scheduler thread)."""
        if not monitor.enabled():
            return
        ttft = (None if h.first_token_ts is None
                else h.first_token_ts - h.submit_ts)
        tpot = (None if (h.first_token_ts is None or n_tokens < 2)
                else (h.finish_ts - h.first_token_ts) / (n_tokens - 1))
        e2e = h.finish_ts - h.submit_ts
        kv_ps = self._kv_page_seconds(h, n_tokens)
        _met, misses = self.slo.record_finish(
            h.tenant, ttft, tpot, e2e, n_tokens, kv_ps)
        t = _slo.tenant_key(h.tenant)
        self._tenant_tokens_counter().labels(
            server=self.monitor_server, tenant=t).inc(n_tokens)
        if kv_ps > 0:
            self._tenant_kv_counter().labels(
                server=self.monitor_server, tenant=t).inc(kv_ps)
        for dim in misses:
            self._slo_miss_counter().labels(
                server=self.monitor_server, tenant=t, slo=dim).inc()
        g = self.slo.goodput(h.tenant)
        if g is not None:
            self._goodput_gauge().labels(
                server=self.monitor_server, tenant=t).set(g)

    def _slo_fail(self, h: RequestHandle) -> None:
        """A FAILED terminal is an SLO miss by definition (the service
        never delivered) — called right after the contained-failure
        ``_count("failed")`` sites. The fatal ``_finalize`` path does
        NOT score: a dying server's burn rate is not an alerting
        signal, it is an outage the healthz status already names."""
        if not monitor.enabled():
            return
        self.slo.record_failure(h.tenant)
        t = _slo.tenant_key(h.tenant)
        self._slo_miss_counter().labels(
            server=self.monitor_server, tenant=t, slo="failed").inc()
        g = self.slo.goodput(h.tenant)
        if g is not None:
            self._goodput_gauge().labels(
                server=self.monitor_server, tenant=t).set(g)

    def _depth_gauge(self) -> None:
        if monitor.enabled():
            self._queue_depth_gauge().labels(
                server=self.monitor_server).set(self.queue.depth)
            self._active_gauge().labels(
                server=self.monitor_server).set(len(self._active))
            if getattr(self.engine, "alloc", None) is not None:
                self._pressure_gauge().labels(
                    server=self.monitor_server).set(
                    self._waiting_on_pages)

    def _count_fault(self, kind: str, site: str) -> None:
        # called from the scheduler thread AND the watchdog — the host
        # dict needs the lock, the monitor counter has its own
        with self._lock:
            key = (kind, site)
            self._fault_counts[key] = self._fault_counts.get(key, 0) + 1
        if monitor.enabled():
            self._faults_counter().labels(
                server=self.monitor_server, kind=kind, site=site).inc()
        # one choke point gives every fault classification a trace
        # event BEFORE any flight dump fires — the dump's final events
        # name the faulting site
        if trace.enabled():
            trace.event("fault", kind=kind, site=site,
                        server=self.monitor_server)

    def _set_degraded(self, reason: str, stall: bool = False) -> None:
        with self._lock:
            self._degraded_reason = reason
            self._stall_flag = stall
        if monitor.enabled():
            self._degraded_gauge().labels(
                server=self.monitor_server).set(1)

    def _clear_degraded(self, stall_only: bool = False) -> None:
        with self._lock:
            if stall_only and not self._stall_flag:
                return
            self._degraded_reason = None
            self._stall_flag = False
        if monitor.enabled():
            self._degraded_gauge().labels(
                server=self.monitor_server).set(0)

    # -- stall watchdog (its own thread; flags only, never the engine) -------
    def _watch(self) -> None:
        """Detect a wedged scheduler step: ``stall_timeout_s`` without
        a loop heartbeat flips status to ``degraded`` (healthz 503) and
        counts a ``stall`` fault — a hung device call can't announce
        itself, so somebody else has to. Clears as soon as the loop
        beats again. Never arms during warmup (compiles are not
        stalls), and never overwrites a recovery's degraded reason."""
        period = min(max(self.stall_timeout_s / 4.0, 0.005), 1.0)
        while not self._stopped.wait(period):
            if not self._ready.is_set():
                continue
            age = time.monotonic() - self._beat
            with self._lock:
                stalled = self._stall_flag
                degraded = self._degraded_reason is not None
            if age > self.stall_timeout_s:
                if not degraded:
                    self._count_fault("stall", "loop")
                    self._set_degraded(
                        f"scheduler step stalled > "
                        f"{self.stall_timeout_s}s", stall=True)
                    # the wedged scheduler thread can't dump its own
                    # black box — the watchdog does it (the ring's own
                    # lock makes the cross-thread read safe)
                    self._flight_dump("stall")
            elif stalled:
                self._clear_degraded(stall_only=True)

    # -- scheduler loop (single thread) --------------------------------------
    def _on_cancel(self, handle: RequestHandle) -> None:
        self._wake.set()

    def _loop(self) -> None:
        err: Optional[BaseException] = None
        if self._watchdog is not None and not self._watchdog.is_alive():
            try:
                self._watchdog.start()
            except RuntimeError:   # already started once
                pass
        try:
            if self.warmup:
                # pre-compile every serving-path program IN the engine-
                # owning thread, off the request path: no user request
                # ever pays an XLA compile. /healthz reports "warming"
                # until this finishes (submissions queue meanwhile).
                self.engine.warmup(self.segment_steps)
            self._beat = time.monotonic()
            self._ready.set()
            while True:
                with self._lock:
                    stopping = self._stopping
                if stopping:
                    break
                # heartbeat the watchdog reads: one "step" is
                # gap + decode segment + collect
                self._beat = time.monotonic()
                try:
                    self._gap()
                    if self._active or self._adm is not None:
                        # with only a chunked admission in flight the
                        # segment is a fast no-op and the loop spins
                        # straight back into _gap for the next chunk
                        sp = trace.NULL_SPAN
                        if trace.enabled() and self._active:
                            # batch-wide event: carries the live
                            # request set so each one's timeline()
                            # includes its segments — plus the LoRA
                            # adapter mix decoding in it (which
                            # fine-tunes shared this program run)
                            ad = tuple(sorted(
                                {h.cfg.adapter for h
                                 in self._active.values()
                                 if getattr(h.cfg, "adapter", None)
                                 is not None}))
                            attrs = {"adapters": ad} if ad else {}
                            sp = trace.span(
                                "segment", steps=self.segment_steps,
                                rids=tuple(h._trace_rid for h
                                           in self._active.values()),
                                **attrs)
                        with sp:
                            self._guard(
                                "decode",
                                lambda: self.engine.decode_segment(
                                    self.segment_steps))
                        self._guard("collect", self._collect)
                    else:
                        with self._idle_cv:
                            self._idle_cv.notify_all()
                        self._wake.wait(self.idle_wait_s)
                        self._wake.clear()
                except _EngineFaultSignal as sig:
                    if not self._recover(sig):
                        raise RuntimeError(
                            f"engine fault at {sig.site} with the "
                            f"restart budget exhausted "
                            f"(max_restarts={self.max_restarts}): "
                            f"{sig.cause!r}") from sig.cause
        except BaseException as e:     # noqa: BLE001 - must not hang clients
            err = e
        finally:
            # terminal cleanup runs HERE, in the engine-owning thread:
            # a dead loop must never strand handles in a non-terminal
            # state (clients block in result()/stream() forever) or
            # leave drain() waiting on a condition nobody will signal.
            self._finalize(err)
            # unblock wait_ready() even when WARMUP itself died — the
            # fatal status is already recorded, and `status` reports
            # failed/stopped before it ever consults _ready
            self._ready.set()
            self._stopped.set()
            with self._idle_cv:
                self._idle_cv.notify_all()

    @property
    def status(self) -> str:
        """``warming`` (pre-compiling, not ready for traffic — requests
        still queue) / ``ok`` / ``degraded`` (stalled step or
        mid-recovery; submissions reject with reason) / ``draining`` /
        ``failed`` (scheduler died on an exception) / ``stopped`` —
        what ``/healthz`` reports (only ``ok``/``draining`` are HTTP
        200)."""
        # lint: allow-unlocked(single atomic ref read; _fatal is
        # written exactly once, on the scheduler's way out — a racing
        # read sees None or the final value, never a torn state)
        if self._fatal is not None:
            return "failed"
        if self._stopped.is_set():
            return "stopped"
        if not self._ready.is_set():
            return "warming"
        with self._lock:
            degraded = self._degraded_reason is not None
        if degraded:
            return "degraded"
        return "draining" if self.draining else "ok"

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until warmup finished (immediately True when
        ``warmup=False``). Also returns when the scheduler DIED during
        warmup — check :attr:`status` (``"failed"``) before serving."""
        return self._ready.wait(timeout)

    def _finalize(self, err: Optional[BaseException]) -> None:
        fail = err is not None
        if fail:
            # the scheduler is dying on an exception: capture the black
            # box BEFORE the handles get their terminal states
            if trace.enabled():
                trace.event("fatal", server=self.monitor_server,
                            cause=repr(err))
            self._flight_dump("scheduler_fatal")
        with self._lock:
            # close the submit door BEFORE draining (on the crash path
            # _stopping is still False here — without this a racing
            # submit could enqueue after the final drain and strand its
            # handle QUEUED forever)
            self._stopping = True
            self._fatal = err
        wrapped = (RuntimeError(f"serving scheduler died: {err!r}")
                   if fail else None)
        # pending adapter admin ops must not strand their callers in
        # load_adapter()'s wait — report the terminal state as an error
        with self._lock:
            admin, self._admin_ops = self._admin_ops, []
        for _op, _args, evt, box in admin:
            box["error"] = (wrapped if fail else
                            RuntimeError("server stopped before the "
                                         "admin op applied"))
            evt.set()
        if self._adm is not None:
            adm, h = self._adm
            self._adm = None
            if not fail:
                try:    # engine coherent on a clean stop — reclaim
                    self.engine.abort_admit(adm)
                except Exception:
                    pass
            h._finish(FAILED if fail else CANCELLED, wrapped)
            self._count("failed" if fail else "cancelled")
        for h in self._replay:
            # replays never reached the rebuilt engine — no capacity to
            # reclaim, just a terminal state so result() can't hang
            h._finish(FAILED if fail else CANCELLED, wrapped)
            self._count("failed" if fail else "cancelled")
        self._replay = []
        for h in self.queue.drain_all():
            h._finish(FAILED if fail else CANCELLED, wrapped)
            self._count("failed" if fail else "cancelled")
        for rid, h in list(self._active.items()):
            if not fail:
                # engine state is coherent on a clean stop — reclaim
                try:
                    self.engine.cancel_request(rid)
                except Exception:
                    pass
            h._finish(FAILED if fail else CANCELLED, wrapped)
            self._count("failed" if fail else "cancelled")
        self._active.clear()

    # -- fault containment ---------------------------------------------------
    def _guard(self, site: str, fn):
        """Run one engine-touching step at a BATCH-wide seam
        (decode/collect/cancel): any non-fatal exception becomes an
        engine-scoped fault signal — there is no single request to
        contain it to, and the shared device state is suspect."""
        try:
            return fn()
        except _EngineFaultSignal:
            raise
        except Exception as e:
            if classify_fault(e, site) == "fatal":  # future-proofing;
                raise                               # fatal is Base-only
            self._count_fault("engine", site)
            self._faulted = True   # drain-visible until _recover ends
            raise _EngineFaultSignal(site, e) from e

    def _contain(self, h: RequestHandle, exc: Exception,
                 site: str) -> None:
        """Fault containment at a REQUEST-scoped seam (admission /
        chunk): classify the blast radius. A request-scoped fault
        finishes ONLY this handle as FAILED with its cause — the
        engine's abort guards already reclaimed the slot and pages —
        and the caller keeps serving everyone else. An engine-scoped
        one escalates to the loop's recovery handler with the
        triggering handle riding along for replay."""
        kind = classify_fault(exc, site)
        if kind == "fatal":
            raise exc
        self._count_fault(kind, site)
        if kind == "request":
            h._finish(FAILED, exc)
            self._count("failed")
            self._slo_fail(h)
            return
        # the handle now rides ONLY inside the signal until _recover
        # parks it — flag the window so a timed drain() can't report
        # "everything finished" while it unwinds
        self._faulted = True
        raise _EngineFaultSignal(site, exc, h) from exc

    def _recover(self, sig: _EngineFaultSignal) -> bool:
        """Supervised engine recovery (scheduler thread): back off
        exponentially, rebuild device state (``engine.reset_state`` —
        compiled programs survive), and requeue every in-flight request
        for REPLAY from its stored prompt + tokens emitted so far.
        Requests past their ``max_replays`` budget fail with the fault
        as cause; cancel-requested ones finish CANCELLED. Returns False
        when the lifetime ``max_restarts`` budget is exhausted, and
        RAISES (carrying the rebuild error) when ``reset_state`` itself
        fails — either way the caller falls through to the fatal
        ``_finalize`` path with an honest diagnosis."""
        # the flight recorder fires FIRST, before any recovery work
        # mutates state: the dump is "what the engine was doing in the
        # seconds before the fault", and it must be written even when
        # the restart budget is already exhausted (the seam's
        # _count_fault event naming the site is already in the ring)
        self._flight_dump(f"engine_fault_{sig.site}")
        try:
            return self._recover_inner(sig)
        finally:
            # every exit parked the signal's handle somewhere a
            # finalizer or the next gap reaches — the drain-visibility
            # window the seams flagged is over
            self._faulted = False

    def _recover_inner(self, sig: _EngineFaultSignal) -> bool:
        if self._restarts >= self.max_restarts:
            # the triggering handle may live in NO collection yet (an
            # admission-seam fault pops it from the queue first) — park
            # it where the fatal _finalize will fail it, never strand it
            if sig.handle is not None:
                self._replay.append(sig.handle)
            return False
        self._restarts += 1      # counts ATTEMPTED-and-allowed restarts
        t0 = time.monotonic()
        self._set_degraded(
            f"recovering from engine fault at {sig.site}: "
            f"{sig.cause!r}")
        if monitor.enabled():
            self._restarts_counter().labels(
                server=self.monitor_server).inc()
        # _admitting makes the whole recovery window visible to a timed
        # drain(): handles leave _active/_adm below and only land back
        # in _replay at the end — without this a drain timing out
        # mid-recovery would report "everything finished"
        self._admitting = True
        try:
            # snapshot in-flight work BEFORE touching the engine: its
            # device state is suspect, so no cancel_request/abort_admit
            # — reset_state reclaims every slot and page wholesale
            inflight = []
            if sig.handle is not None:
                inflight.append(sig.handle)
            if self._adm is not None:
                _, h = self._adm
                self._adm = None
                inflight.append(h)
            inflight.extend(self._active.values())
            self._active.clear()
            # transient device faults (preemption, collective timeout)
            # need breathing room before the rebuild retries the device
            # — but the backoff must stay interruptible: a shutdown
            # racing a fault storm cannot wait out 2s sleeps
            end = time.monotonic() + min(
                self.restart_backoff_s * (2 ** (self._restarts - 1)),
                self.restart_backoff_max_s)
            with trace.span("backoff", site=sig.site,
                            restart=self._restarts):
                while True:
                    with self._lock:
                        stopping = self._stopping
                    rem = end - time.monotonic()
                    if stopping or rem <= 0:
                        break
                    time.sleep(min(0.05, rem))
            if stopping:
                # shutdown won the race: park the in-flight handles for
                # the loop's exit cleanup (clean stop → CANCELLED,
                # crash → FAILED; never stranded) — but still rebuild
                # best-effort: the engine is CALLER-owned and outlives
                # this server, so a raced stop must not hand back an
                # engine with poisoned device state and leaked
                # slots/pages (reset is cheap — no compiles)
                self._replay.extend(inflight)
                self._clear_degraded()
                try:
                    self.engine.reset_state()
                except Exception:
                    pass
                return True
            try:
                self.engine.reset_state()
                if trace.enabled():
                    trace.event("restart", site=sig.site,
                                restarts=self._restarts,
                                inflight=len(inflight))
            except Exception as rebuild_err:
                # the rebuild itself failed — nothing left to try. The
                # snapshotted handles were already pulled out of
                # _active/_adm; park them in _replay so the fatal
                # _finalize reaches every one (result() must never
                # hang), drop the stale "recovering" degraded reason
                # (the terminal status is "failed", not failed-but-
                # mid-recovery), and DIAGNOSE honestly: the fatal error
                # must carry the rebuild failure, not claim a restart
                # budget that was never exhausted
                self._replay.extend(inflight)
                self._clear_degraded()
                self._count_fault("engine", "reset")
                raise RuntimeError(
                    f"engine rebuild (reset_state) failed during "
                    f"recovery from the {sig.site} fault "
                    f"{sig.cause!r}: {rebuild_err!r}") from rebuild_err
            for h in inflight:
                if h._cancel_requested:
                    h._finish(CANCELLED)
                    self._count("cancelled")
                    continue
                h._replays += 1
                if h._replays > self.max_replays:
                    h._finish(FAILED, RuntimeError(
                        f"request {h.id} exceeded its replay budget "
                        f"(max_replays={self.max_replays}) across "
                        f"engine restarts; last fault at {sig.site}: "
                        f"{sig.cause!r}"))
                    self._count("failed")
                    self._slo_fail(h)
                else:
                    self._replay.append(h)
        finally:
            self._admitting = False
        dt = time.monotonic() - t0
        with self._lock:
            self._recovery_s.append(dt)
        if monitor.enabled():
            self._recovery_hist().labels(
                server=self.monitor_server).observe(dt)
        if trace.enabled():
            trace.record("recover", dur_ns=int(dt * 1e9), site=sig.site,
                         restarts=self._restarts)
        # refresh the heartbeat BEFORE dropping the degraded flag: the
        # beat is stale by the whole recovery (backoff included), and a
        # watchdog tick landing between the clear and the loop's next
        # beat would record a phantom stall
        self._beat = time.monotonic()
        self._clear_degraded()
        self._depth_gauge()
        return True

    # -- admission helpers ---------------------------------------------------
    def _start_admission(self, h: RequestHandle, ids, cfg,
                         plen: int) -> bool:
        """Admit one request NOW (capacity already probed): one-shot,
        or begin a chunked admission for prompts longer than the
        engine's ``prefill_chunk``. Returns True when the request is
        live (or its chunked admission is in flight); False when a
        request-scoped fault failed the handle (capacity reclaimed by
        the engine's abort guards). Engine-scoped faults escalate via
        :meth:`_contain`."""
        chunk = getattr(self.engine, "prefill_chunk", None)
        # the adapter id rides every admission span: a multi-tenant
        # timeline must say WHOSE weights the prefill ran under
        t_attrs = ({"adapter": cfg.adapter}
                   if getattr(cfg, "adapter", None) is not None else {})
        if chunk is not None and plen > chunk:
            # long prompt: claim capacity now, prefill one fixed-shape
            # chunk per gap (decode segments run in between) instead of
            # one monopolizing prefill
            sp = trace.NULL_SPAN
            if trace.enabled():
                sp = trace.span("admit.begin", rid=h._trace_rid,
                                plen=plen, chunk=chunk,
                                replay=h._engine_base > 0, **t_attrs)
            with sp:
                try:
                    adm = self.engine.begin_admit(ids, cfg)
                except Exception as e:
                    self._contain(h, e, "admit")
                    return False
            self._adm = (adm, h)
            return True
        sp = trace.NULL_SPAN
        if trace.enabled():
            wfn = getattr(self.engine, "_prefill_width", None)
            sp = trace.span("admit", rid=h._trace_rid, plen=plen,
                            bucket=(wfn(plen) if wfn is not None
                                    else plen),
                            replay=h._engine_base > 0, **t_attrs)
        with sp:
            try:
                rid = self.engine.add_request(ids, cfg)
            except Exception as e:
                self._contain(h, e, "admit")
                return False
        h._mark_running(rid)
        self._active[rid] = h
        # admission prefill already sampled the first token: push it
        # now — the TTFT edge for the handle's stream
        toks = self.engine.partial_tokens(rid)
        if toks is not None:
            self._push_delta(h, toks)
        return True

    def _admit_replays(self) -> None:
        """Re-admit requests surviving an engine restart OR a
        memory-pressure preemption, FIRST (before new queue work): they
        already held capacity when the fault/preemption hit. In
        reserved mode a replay reserves exactly what the original did
        (prompt + full budget), so the rebuilt engine always has room;
        in optimistic mode the claim is prompt + one page and a replay
        defers while the pool is crowded (new-queue admission stays
        paused until every replay is back in — pressure victims are
        owed their pages before fresh traffic). At worst a replay
        longer than ``prefill_chunk`` waits its turn behind the single
        in-flight chunked admission.

        A replay re-prefills ``prompt + tokens emitted so far`` (the
        bucketed/chunked machinery treats it like any prompt) with the
        budget reduced by what was already emitted. Greedy replay is
        bitwise-identical to the uninterrupted decode (causal prefill
        of the same prefix); sampled requests continue on a fresh noise
        stream. The admission deadline applies only to a handle that
        never COMPLETED an admission (``engine_rid is None`` — a
        pressure-abort of its in-flight chunked claim parked it here):
        once a request admitted, the deadline was met and a replay
        must not expire it. Deferral is O(1) — the O(plen)
        replay-prompt build only happens on the gap that actually
        admits."""
        pending, self._replay = self._replay, []
        still = []
        chunk = getattr(self.engine, "prefill_chunk", None)
        # drain visibility: the caller (_gap) holds _admitting for its
        # whole body, covering the window where handles live only in
        # these locals
        try:
            while pending:
                h = pending.pop(0)
                if h._cancel_requested:
                    h._finish(CANCELLED)
                    self._count("cancelled")
                    continue
                if (h.engine_rid is None and h.deadline is not None
                        and time.monotonic() >= h.deadline):
                    h._finish(EXPIRED)
                    self._count("expired")
                    continue
                n_toks = h._n_pushed    # == len(h._tokens): scheduler-
                #                         thread bookkeeping, O(1)
                remaining = h.cfg.max_new_tokens - n_toks
                if remaining < 1:
                    # fully emitted before the fault (retirement raced
                    # the crash) — it is simply finished
                    h._finish(FINISHED)
                    self._count("completed")
                    if monitor.enabled():
                        self._slo_finish(h, n_toks)
                    continue
                plen = h.prompt_len + n_toks
                if (chunk is not None and plen > chunk
                        and self._adm is not None):
                    still.append(h)     # waits behind the in-flight
                    continue            # chunked admission
                # every config field carries over verbatim (vars(), not
                # a hand-written field list — a field added to
                # GenerationConfig later must not silently reset to its
                # default on replay); only the budget shrinks
                kw = dict(vars(h.cfg))
                kw["max_new_tokens"] = remaining
                rcfg = GenerationConfig(**kw)
                if not self.engine.can_admit(plen, rcfg):
                    if (not self._active and self._adm is None
                            and self.engine.free_slots()
                            == self.engine.max_batch):
                        # the engine is completely IDLE and the replay
                        # still cannot fit: prompt + generated has
                        # outgrown what the pool can EVER hold (a
                        # preempted request's replay prompt includes
                        # every emitted token) — fail loudly with the
                        # typed cause instead of deferring forever
                        # against an empty engine
                        h._finish(FAILED, PagePoolExhausted(
                            [h.id],
                            f"replay of request {h.id} "
                            f"(prompt+generated={plen} tokens) can "
                            f"never be admitted: engine capacity "
                            f"(page pool / max_len) is too small "
                            f"even when idle"))
                        self._count("failed")
                        self._slo_fail(h)
                        continue
                    still.append(h)
                    continue
                # lint: allow-host-sync(host-list copy, no device
                # read: tokens_so_far() is the handle's python list)
                ids = np.concatenate(
                    [_prompt_ids(h.prompt)[0],
                     np.asarray(h.tokens_so_far(), np.int32)]) \
                    if n_toks else _prompt_ids(h.prompt)[0]
                # the engine's token list restarts at 0 for the
                # replayed rid; handle-side indices keep counting from
                # the full history
                h._engine_base = n_toks
                if trace.enabled():
                    # re-admission after an engine restart OR a
                    # memory-pressure preemption: the timeline shows
                    # replay -> admit(replay=True) -> segments
                    trace.event("replay", rid=h._trace_rid,
                                emitted=n_toks, replays=h._replays,
                                preempts=h._preempts)
                self._start_admission(h, ids, rcfg, plen)
        finally:
            # an engine-fault signal mid-iteration leaves the
            # unprocessed tail (and the deferred ones) queued for the
            # next recovery/gap — nothing is stranded or duplicated
            self._replay = still + pending + self._replay

    def _gap(self) -> None:  # lint: hot-path
        """The inter-segment gap: cancellations first (they free
        capacity), then ONE chunk of any in-flight chunked admission
        (bounded gap work — decode segments run between chunks), then
        expiry reaping, then replay re-admissions, then admission while
        the engine's capacity probe allows.

        ``_admitting`` is held for the WHOLE gap: at several points a
        handle lives only in locals (mid-admission, mid-replay, the
        chunk-abort window) and a timed ``drain()`` must never see
        "queue empty, nothing active" through one of them.

        Pressure relief runs LAST (optimistic paged mode): every slot
        the coming segment will write is grown now, preempting victims
        if the pool is dry — so ``decode_segment``'s own exhaustion
        guard (:class:`PagePoolExhausted`, an engine-scoped fault)
        never fires under this scheduler."""
        self._admitting = True
        # the gap span only when there is WORK: an idle loop gaps ~50x/s
        # and would drown the flight ring in empty spans
        busy = bool(trace.enabled()
                    and (self._active or self._adm is not None
                         or self._replay or self.queue.depth))
        try:
            with (trace.span("gap") if busy else trace.NULL_SPAN):
                self._gap_body()
            self._relieve_pressure()
            if self.control is not None:
                # observe->act loop last, on the post-admission state
                # (rate-limited inside ControlPlane.tick): pure host
                # bookkeeping, no engine work
                self._control_tick()
        finally:
            self._admitting = False
        self._depth_gauge()

    def _gap_body(self) -> None:
        # 0. adapter admin (hot load/unload) applies FIRST — "in the
        #    inter-segment gap" is the registry's whole thread contract,
        #    and a load should be visible to this gap's admissions
        # lint: allow-unlocked(atomic emptiness probe on the hot path;
        # _apply_admin re-reads and swaps the list under _lock)
        if self._admin_ops:
            self._apply_admin()
        # 1. cancellations of RUNNING requests retire their slots
        for rid, h in list(self._active.items()):
            if h._cancel_requested:
                toks = self._guard(
                    "cancel",
                    lambda rid=rid: self.engine.cancel_request(rid))
                del self._active[rid]
                if toks is not None:
                    self._push_delta(
                        h, list(toks[h._n_pushed - h._engine_base:]))
                h._finish(CANCELLED)
                self._count("cancelled")
        # 1b. advance the in-flight chunked admission by ONE fixed-shape
        #     chunk (or abandon it if its client cancelled / its
        #     admission deadline passed — chunked admission spans many
        #     gaps, so queue.reap alone no longer covers the whole wait
        #     for admission): admission work per gap stays bounded no
        #     matter how long the prompt
        if self._adm is not None:
            adm, h = self._adm
            # the deadline is an ADMISSION deadline: a chunked REPLAY
            # (_engine_base > 0 — the request already admitted once and
            # emitted tokens) met it the first time and must not expire
            # mid-recovery
            expired = (h.deadline is not None and h._engine_base == 0
                       and time.monotonic() >= h.deadline)
            if h._cancel_requested or expired:
                self._adm = None
                h._finish(CANCELLED if h._cancel_requested else EXPIRED)
                self._count("cancelled" if h._cancel_requested
                            else "expired")
                # the handle is terminal first: if the abort itself
                # faults, recovery reclaims capacity wholesale and the
                # client is not stranded behind the engine's health
                self._guard("cancel",
                            lambda: self.engine.abort_admit(adm))
            else:
                sp = trace.NULL_SPAN
                if trace.enabled():
                    sp = trace.span("prefill_chunk", rid=h._trace_rid,
                                    off=getattr(adm, "off", None))
                try:
                    with sp:
                        finished = self.engine.admit_chunk(adm)
                except Exception as e:
                    self._adm = None
                    # admit_chunk aborts itself on ITS failures, but a
                    # fault at the call seam (injection, wrapper bug)
                    # leaves the claim open — abort_admit is idempotent,
                    # so reclaim unconditionally before containment
                    try:
                        self.engine.abort_admit(adm)
                    except Exception:
                        pass   # engine-scoped path: reset reclaims all
                    self._contain(h, e, "chunk")
                else:
                    if finished:
                        self._adm = None
                        h._mark_running(adm.rid)
                        self._active[adm.rid] = h
                        if trace.enabled():
                            trace.event("admit.done", rid=h._trace_rid,
                                        chunked=True)
                        toks = self.engine.partial_tokens(adm.rid)
                        if toks is not None:
                            self._push_delta(h, toks)
        # 2. cancelled/expired queue entries never admit
        for h in self.queue.reap(time.monotonic()):
            if trace.enabled():
                trace.event("queue.expire", rid=h._trace_rid,
                            cancelled=h._cancel_requested)
            if h._cancel_requested:
                h._finish(CANCELLED)
                self._count("cancelled")
            else:
                h._finish(EXPIRED)
                self._count("expired")
        # 2b. replays surviving an engine restart re-admit before new
        #     queue work (their capacity claim predates the fault)
        if self._replay:
            self._admit_replays()
        if self._replay:
            # replays still pending (e.g. waiting behind the single
            # chunked admission): do NOT admit new queue work this gap
            # — fresh traffic would claim the pages/slots the replays'
            # pre-fault reservations are owed, starving them behind
            # arrivals that keep refilling the pool
            return
        # 3. admission: probe, never catch capacity — deferral is the
        #    scheduler path, add_request raising is the programmer-error
        #    path; a raise that happens anyway is a FAULT and goes
        #    through containment (_contain). The caller's _admitting
        #    span covers the whole pop→_active window (prefill can be
        #    seconds on a first compile).
        chunk = getattr(self.engine, "prefill_chunk", None)

        def admittable(h) -> bool:
            if not self.engine.can_admit(h.prompt_len, h.cfg):
                return False
            if (chunk is not None and h.prompt_len > chunk
                    and self._adm is not None):
                # one chunked admission at a time: a second long prompt
                # defers until the in-flight one completes (its slot and
                # pages are already claimed, so capacity stays honest)
                return False
            return True

        while True:
            if self.tenant_quotas is None:
                h = self.queue.pop_if(admittable)
            else:
                # quota-aware pop: a tenant over its cap defers ITS
                # entries only — tenants queued behind it still admit
                # (capacity-blocked heads still stop the scan: no
                # head-of-line bypass on capacity)
                h = self.queue.pop_admittable(admittable,
                                              self._tenant_ok)
            if h is None:
                # head (if any) does not fit RIGHT NOW. With the
                # engine completely idle it can never fit — fail it
                # loudly instead of wedging the queue forever. The
                # pop re-checks the probe under the queue lock: a
                # racing submit may have put a NEW, admittable head
                # in front, which must not be the one failed.
                if (self.queue.depth and not self._active
                        and self.engine.free_slots()
                        == self.engine.max_batch):
                    bad = self.queue.pop_if(
                        lambda h: not self.engine.can_admit(
                            h.prompt_len, h.cfg))
                    if bad is not None:
                        bad._finish(FAILED, RuntimeError(
                            f"request {bad.id} (prompt_len="
                            f"{bad.prompt_len}, max_new_tokens="
                            f"{bad.cfg.max_new_tokens}) can never "
                            "be admitted: engine capacity (page "
                            "pool / max_len) is too small even "
                            "when idle"))
                        self._count("failed")
                        self._slo_fail(bad)
                    continue
                break
            wait_s = time.monotonic() - h.submit_ts
            if monitor.enabled():
                # queue-wait digest: the admission-delay share of the
                # tenant's latency story (replays never pass here — a
                # replay wait is recovery, not queueing)
                self.slo.observe("queue_wait", h.tenant, wait_s)
            if trace.enabled():
                trace.event("queue.dequeue", rid=h._trace_rid,
                            wait_s=round(wait_s, 6))
            if self.control is not None:
                # brownout rungs 2/3 degrade the request AT admission
                # (cap max_new_tokens, strip speculation): the handle's
                # cfg is replaced so a later preemption REPLAYS the
                # degraded budget — never the original. Already-admitted
                # requests are untouched (rung transitions are bitwise-
                # neutral for them); a no-op rung returns cfg unchanged.
                h.cfg = self.control.degrade_cfg(h.cfg)
            self._start_admission(h, h.prompt, h.cfg, h.prompt_len)

    def _tenant_ok(self, h: RequestHandle) -> bool:
        """Per-tenant quota probe (scheduler thread): True when
        admitting ``h`` now keeps its tenant at or under its cap.
        Counts ADMITTED work — active slots plus the in-flight chunked
        admission; replays are exempt (they held capacity when the
        fault/preemption hit, and re-admission must not deadlock
        behind the quota they already consumed once)."""
        q = self.tenant_quotas
        if q is None or h.tenant is None:
            return True
        cap = q if isinstance(q, int) else q.get(h.tenant)
        if cap is None:
            return True
        if self.control is not None:
            # brownout rung 1: every quotaed tenant's effective cap is
            # halved (min 1) while the ladder is engaged — the gentlest
            # rung, shaving concurrency before any request degrades
            cap = self.control.quota_cap(cap)
        n = sum(1 for hh in self._active.values()
                if hh.tenant == h.tenant)
        if self._adm is not None and self._adm[1].tenant == h.tenant:
            n += 1
        return n < cap

    def _control_tick(self) -> None:
        """One control-plane pass in the gap (scheduler thread;
        rate-limited inside :meth:`ControlPlane.tick`): feed the SLO
        tracker's per-tenant burn windows + queue occupancy in, apply
        what comes out — shed windows deprioritize the tenant's
        ALREADY-QUEUED entries into the penalty band (new arrivals 429
        at submit), rung transitions trace/export and flip the one
        engine-side actuator (prefix-cache admission pause, a host
        bool — the paused path is the already-warmed cold admission,
        so no rung compiles anything)."""
        dec = self.control.tick(
            time.monotonic(),
            queue_depth=self.queue.depth,
            max_queue=self.queue.max_size,
            tenant_stats=(self.slo.tenant_stats()
                          if monitor.enabled() else None))
        if dec is None:
            return
        band = self.control.policy.penalty_band
        for tenant, until in dec["shed"]:
            self.queue.penalize(tenant, band, until)
            if trace.enabled():
                trace.event("control.shed", tenant=tenant,
                            reason="burn_window",
                            window_s=round(
                                until - time.monotonic(), 3),
                            server=self.monitor_server)
        for tenant in dec["unshed"]:
            self.queue.unpenalize(tenant)
        if dec["rung"] != dec["prev_rung"]:
            if trace.enabled():
                trace.event("control.rung", rung=dec["rung"],
                            prev=dec["prev_rung"],
                            action=RUNG_ACTIONS[dec["rung"]],
                            occupancy=round(dec["occupancy"], 4),
                            server=self.monitor_server)
            if monitor.enabled():
                self._rung_gauge().labels(
                    server=self.monitor_server).set(dec["rung"])
            if getattr(self.engine, "prefix_cache", False):
                # rung 4 actuator: pause prefix-cache admission (new
                # requests take the cold path — no CoW pages minted
                # under overload). The scheduler thread owns the
                # engine; getattr/setattr routes through a FaultyEngine
                # proxy to the wrapped engine.
                self.engine.prefix_pause = dec["rung"] >= 4

    # -- memory pressure (optimistic paged mode; scheduler thread) -----------
    def _relieve_pressure(self) -> None:
        """Resolve KV memory pressure in the gap (optimistic admission
        mode only; a no-op otherwise): grow every live slot's page
        mapping for the coming segment, and while the pool cannot
        cover the growth, PREEMPT victims — most SLO headroom first
        (no admission deadline beats any deadline, then furthest from
        it), ties by lowest priority (highest priority value) then
        youngest (highest rid), NEVER
        the oldest surviving request, so the head of the line always
        makes forward progress and pressure can never deadlock or
        livelock the loop. A preempted request's slot and pages are
        reclaimed immediately (``engine.preempt_request``) and its
        handle parks on the replay list — the SAME machinery as
        engine-restart replay, so it re-admits through the normal
        bucketed/chunked prefill with its generated tokens intact
        (greedy preempt-resume is bitwise-identical to an unpreempted
        run) — bounded per request by ``max_preemptions``. A request
        the pool cannot cover even ALONE fails with
        :class:`PagePoolExhausted` as its typed cause: a
        request-scoped, CONTAINED event, not an engine-scoped fault
        (full restart + replay of everyone)."""
        eng = self.engine
        if getattr(eng, "admission_mode", None) != "optimistic":
            return
        sp = trace.NULL_SPAN
        if trace.enabled() and (self._active or self._adm is not None):
            sp = trace.span("gap.pressure", active=len(self._active))
        with sp:
            self._relieve_pressure_body()

    def _relieve_pressure_body(self) -> None:
        eng = self.engine
        while True:
            short = self._guard(
                "pressure",
                lambda: eng.grow_for_segment(self.segment_steps))
            if not short:
                break
            # age is the HANDLE's submit time, not the engine rid: a
            # replayed request re-admits under a fresh (higher) rid but
            # keeps its seniority — preempting it again just because it
            # was once a victim would be a thrash amplifier
            oldest = (min(self._active,
                          key=lambda r: (self._active[r].submit_ts,
                                         self._active[r].id))
                      if self._active else None)
            cands = [r for r in self._active if r != oldest]
            if cands:
                # deadline-aware victim ordering (ISSUE 19): preempt
                # the request with the MOST SLO headroom first — one
                # with no deadline at all (inf headroom) before any
                # with one, then furthest-from-deadline. Ties (the
                # whole field, when no deadlines are set) fall back to
                # the PR-5 ordering: lowest priority (highest value),
                # then youngest — deterministic either way.
                now = time.monotonic()
                victim = max(cands, key=lambda r:
                             ((float("inf")
                               if self._active[r].deadline is None
                               else self._active[r].deadline - now),
                              self._active[r].priority,
                              self._active[r].submit_ts,
                              self._active[r].id))
                self._preempt(victim, "pressure")
                continue
            if self._adm is not None:
                # last capacity holder left: the in-flight chunked
                # admission's page claim — abort it (reclaims slot AND
                # pages) and park its handle; replay restarts the
                # prefill from scratch through the same chunked path.
                # The handle parks BEFORE the abort guard: if the
                # abort itself faults, recovery finds it in _replay
                # (reset_state reclaims capacity wholesale) instead of
                # stranding it in a local
                adm, h = self._adm
                self._adm = None
                alloc = getattr(eng, "alloc", None)
                if alloc is not None:
                    alloc.count_preemption("pressure")
                self._park_preempted(h)
                self._guard("cancel",
                            lambda: eng.abort_admit(adm))
                continue
            # nothing left to preempt (only the oldest survivor can
            # still be active): the short request cannot grow even
            # with the pool to itself — preempt-and-replay would hit
            # the same wall forever, so fail it with the typed cause
            progressed = False
            for rid in short:
                toks = self._guard(
                    "pressure",
                    lambda rid=rid: eng.preempt_request(
                        rid, reason="unsatisfiable"))
                h = self._active.pop(rid, None)
                if toks is None and h is None:
                    continue       # foreign/stale rid: nothing owned
                progressed = True
                if h is None:
                    continue       # foreign request (engine driven
                #                    outside this server) — reclaimed
                if toks is not None:
                    self._push_delta(
                        h, list(toks[h._n_pushed - h._engine_base:]))
                h._finish(FAILED, PagePoolExhausted(
                    [rid],
                    f"request {h.id} cannot grow its KV mapping even "
                    f"with the pool to itself (prompt+generated="
                    f"{h.prompt_len + h._n_pushed} tokens, pool="
                    f"{eng.num_pages}x{eng.page_size} tokens) — grow "
                    f"num_pages or lower max_new_tokens"))
                self._count("failed")
                self._slo_fail(h)
            if not progressed:
                # a short rid this scheduler does not own and cannot
                # reclaim: let decode_segment's own exhaustion guard
                # surface it rather than spin in the gap
                break
        self._waiting_on_pages = sum(
            1 for h in self._replay if h._preempts > 0)

    def _preempt(self, rid: int, reason: str) -> None:
        """Preempt ONE active request: the engine reclaims its slot
        and pages (``preempt_request`` — the same reclaim as cancel),
        its tokens so far are pushed to the handle FIRST (the replay
        prompt is prompt + ALL generated tokens — drop one and greedy
        resume parity breaks), then the handle parks for replay."""
        toks = self._guard(
            "pressure",
            lambda: self.engine.preempt_request(rid, reason))
        h = self._active.pop(rid, None)
        if h is None:
            return
        if toks is not None:
            self._push_delta(
                h, list(toks[h._n_pushed - h._engine_base:]))
        self._park_preempted(h)

    def _park_preempted(self, h: RequestHandle) -> None:
        """Park a preempted handle on the replay list (next gap's
        ``_admit_replays`` re-prefills prompt + generated through
        normal admission), enforcing its ``max_preemptions`` budget:
        past it the request is THRASHING (admitted, preempted,
        replayed, preempted again...) and fails with
        :class:`PreemptionBudgetExceeded` instead of cycling through
        the pool forever. A cancel-requested handle finishes CANCELLED
        (``_finish`` is idempotent — terminal exactly once)."""
        if h._cancel_requested:
            h._finish(CANCELLED)
            self._count("cancelled")
            return
        h._preempts += 1
        self._count("preempted")
        if trace.enabled():
            trace.event("preempt", rid=h._trace_rid,
                        preempts=h._preempts, emitted=h._n_pushed)
        # preemption-STORM flight trigger: no single preemption is a
        # fault, but a thrashing pool is exactly the state a postmortem
        # needs the black box for (scheduler thread only)
        now = time.monotonic()
        self._preempt_ts.append(now)
        cut = now - self.STORM_WINDOW_S
        while self._preempt_ts and self._preempt_ts[0] < cut:
            self._preempt_ts.pop(0)
        if (len(self._preempt_ts) >= self.STORM_PREEMPTS
                and now - self._last_storm_dump > self.STORM_WINDOW_S):
            if trace.enabled():
                trace.event("preempt.storm",
                            count=len(self._preempt_ts),
                            window_s=self.STORM_WINDOW_S)
            # re-arm only on a WRITTEN dump: a storm trip with tracing
            # off must not burn the window and suppress the first real
            # dump after an operator enables tracing mid-storm
            if self._flight_dump("preemption_storm") is not None:
                self._last_storm_dump = now
        if h._preempts > self.max_preemptions:
            h._finish(FAILED, PreemptionBudgetExceeded(
                f"request {h.id} preempted {h._preempts} times under "
                f"KV memory pressure (max_preemptions="
                f"{self.max_preemptions}): the pool is too small for "
                f"this request mix — grow num_pages, lower "
                f"kv_watermark, or raise max_preemptions"))
            self._count("failed")
            self._slo_fail(h)
            return
        self._replay.append(h)

    def _push_delta(self, h: RequestHandle, toks) -> None:
        """Push newly generated tokens (scheduler thread only);
        ``_n_pushed`` keeps each gap's copy O(delta), and the first
        push is the TTFT observation."""
        h._n_pushed += len(toks)
        if h._push(toks) and monitor.enabled():
            ttft = h.first_token_ts - h.submit_ts
            self._ttft_hist().labels(server=self.monitor_server).observe(
                ttft)
            # per-tenant TTFT digest (observed at the edge so /stats
            # reflects it while the request still streams; record_finish
            # scores the SLO verdict from the same stamps later)
            self.slo.observe("ttft", h.tenant, ttft)

    def _collect(self) -> None:
        """Post-segment: finish retired requests, stream deltas for the
        still-running ones. Engine-side token indices are offset by a
        replayed handle's ``_engine_base`` (tokens emitted before the
        last restart live only handle-side)."""
        for rid, seq in self.engine.collect_finished().items():
            h = self._active.pop(rid, None)
            if h is None:      # foreign request (user drove the engine)
                continue
            self._push_delta(
                h, list(seq[h._n_pushed - h._engine_base:]))
            h._finish(FINISHED)
            self._count("completed")
            if monitor.enabled():
                n = len(seq) + h._engine_base
                if h.first_token_ts is not None and n > 1:
                    self._tpot_hist().labels(
                        server=self.monitor_server).observe(
                        (h.finish_ts - h.first_token_ts) / (n - 1))
                self._slo_finish(h, n)
        for rid, h in list(self._active.items()):
            delta = self.engine.partial_tokens(
                rid, h._n_pushed - h._engine_base)
            if delta:
                self._push_delta(h, delta)
        self._depth_gauge()
