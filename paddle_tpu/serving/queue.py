"""Request queue + per-request handles for the online serving layer.

The reference server stack sits above AnalysisPredictor and owns the
request lifecycle (accept → queue → schedule → stream → finish); this
module is the lifecycle half of our equivalent: a bounded, priority- and
deadline-aware :class:`RequestQueue` feeding the scheduler, and a
:class:`RequestHandle` the client holds — blocking ``result()``, an
incremental token-``stream()`` iterator, and ``cancel()``.

Thread model: clients (HTTP handler threads, user threads) touch ONLY
the handle's public surface and ``RequestQueue.put``; every state
transition (admit, push tokens, finish, expire) is driven by the single
scheduler thread, so the engine itself never needs a lock.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from .. import tracing as trace

__all__ = [
    "RequestHandle", "RequestQueue", "RequestRejected", "QueueFull",
    "RequestCancelled", "DeadlineExpired", "RequestFailed",
    "QUEUED", "RUNNING", "FINISHED", "CANCELLED", "EXPIRED", "FAILED",
]

# handle lifecycle states
QUEUED = "queued"
RUNNING = "running"
FINISHED = "finished"
CANCELLED = "cancelled"
EXPIRED = "expired"
FAILED = "failed"
_TERMINAL = (FINISHED, CANCELLED, EXPIRED, FAILED)


class RequestRejected(RuntimeError):
    """Backpressure rejection at submit time (the HTTP layer maps this
    to 429/503). ``reason`` is machine-readable; the message says what
    the client should do about it. ``retry_after_s`` (when set) is the
    server's honest wait estimate — a shed rejection derives it from
    the remaining burn window and the HTTP layer turns it into a
    ``Retry-After`` header."""

    def __init__(self, reason: str, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s


class QueueFull(RequestRejected):
    """The bounded request queue is at capacity — retry later (429)."""

    def __init__(self, max_size: int):
        super().__init__(
            "queue_full",
            f"request queue full ({max_size} waiting); retry later")


class RequestCancelled(RuntimeError):
    """``result()`` on a request that was cancelled; partial tokens stay
    readable via ``handle.tokens_so_far()``."""


class DeadlineExpired(RuntimeError):
    """``result()`` on a request whose deadline passed before admission."""


class RequestFailed(RuntimeError):
    """``result()`` on a request that FAILED: one the scheduler could
    never run (e.g. a prompt that cannot ever fit the engine's page
    pool), one whose admission hit a request-scoped fault (the cause
    rides in the message; everyone else kept serving), or one that
    exceeded its replay budget across engine restarts."""


class RequestHandle:
    """One submitted request's client-side handle.

    - ``result(timeout)`` blocks for the full generated ids (prompt NOT
      included, matching ``engine.serve()``), raising
      :class:`RequestCancelled` / :class:`DeadlineExpired` /
      :class:`RequestFailed` on the non-finish terminals;
    - ``stream(timeout)`` / iteration yields token ids INCREMENTALLY as
      decode segments emit them — the first token arrives long before
      the request finishes (that gap is the TTFT the bench reports);
    - ``cancel()`` flags the request; the scheduler retires its slot at
      the next inter-segment gap (capacity is reclaimed, not leaked).

    ``submit_ts`` / ``first_token_ts`` / ``finish_ts`` are
    ``time.monotonic()`` stamps the serving metrics (TTFT, TPOT) are
    derived from.
    """

    def __init__(self, req_id: int, prompt, prompt_len: int, cfg,
                 priority: int = 0, deadline: Optional[float] = None,
                 on_cancel: Optional[Callable[["RequestHandle"], None]]
                 = None, tenant: Optional[str] = None):
        self.id = req_id
        self.prompt = prompt
        self.prompt_len = prompt_len
        self.cfg = cfg
        self.priority = priority
        # tenant identity for per-tenant admission quotas (None =
        # untracked): the scheduler defaults it to the request's LoRA
        # adapter name — in multi-tenant LoRA serving the fine-tune IS
        # the tenant — but an explicit tenant can group requests across
        # adapters (or quota base-model traffic)
        self.tenant = tenant
        self.deadline = deadline          # absolute time.monotonic()
        self.engine_rid: Optional[int] = None
        self.submit_ts = time.monotonic()
        self.admit_ts: Optional[float] = None   # FIRST admission (the
        #                      SLO tracker's KV-page-second integral
        #                      starts here; replays keep the original)
        self.first_token_ts: Optional[float] = None
        self.finish_ts: Optional[float] = None
        self._cv = threading.Condition()
        self._tokens: List[int] = []
        self._n_pushed = 0   # scheduler-thread bookkeeping: tokens the
        #                      scheduler has already pushed, so each
        #                      segment pushes a delta (O(new tokens),
        #                      not a re-copy of the whole history)
        self._status = QUEUED
        self._error: Optional[BaseException] = None
        self._cancel_requested = False
        self._on_cancel = on_cancel
        # supervised-recovery bookkeeping (scheduler thread only):
        # _replays counts engine restarts this request survived (each
        # re-prefills prompt + tokens emitted so far; bounded by the
        # server's max_replays); _engine_base is the handle-side token
        # count at the LAST replay admission — the engine's token list
        # restarts at 0 there, so engine index = handle index - base.
        # _preempts counts memory-pressure preemptions (same replay
        # machinery, separate budget: the server's max_preemptions)
        self._replays = 0
        self._preempts = 0
        self._engine_base = 0
        # trace key (paddle_tpu.tracing): the serving scheduler stamps
        # "<server_label>:<id>" at submit so concurrent servers' request
        # ids never collide in the process-wide ring; a bare handle
        # (tests driving the queue directly) traces under its raw id.
        # _trace_ttft: whether THIS handle's first push is the
        # client-visible TTFT edge — False for a replica-inner handle
        # living under a router-supplied rid (the RouterHandle emits
        # the one true first_token; a failover resubmit's first push
        # is mid-stream, not a TTFT edge)
        self._trace_rid = None
        self._trace_ttft = True

    # -- client surface ------------------------------------------------------
    @property
    def status(self) -> str:
        with self._cv:
            return self._status

    @property
    def done(self) -> bool:
        with self._cv:
            return self._status in _TERMINAL

    def cancel(self) -> None:
        """Request cancellation (idempotent). A queued request is dropped
        at the next admission pass; a running request's slot (and pages)
        is retired at the next inter-segment gap."""
        with self._cv:
            if self._status in _TERMINAL:
                return
            self._cancel_requested = True
        if self._on_cancel is not None:
            self._on_cancel(self)

    def tokens_so_far(self) -> List[int]:
        with self._cv:
            return list(self._tokens)

    def timeline(self) -> List[dict]:
        """This request's ordered trace-event timeline (see
        ``paddle_tpu.tracing``): queue → admit → segments →
        (preempt → replay …) → finish, assembled on demand from the
        process-wide ring. Requires tracing to have been ENABLED while
        the request ran (``FLAGS_enable_trace``); returns ``[]``
        otherwise, and may be partial for a long-finished request (the
        ring is bounded). The timeline is keyed by the HANDLE id, not
        the engine rid, so it survives preempt-replay and engine
        restarts."""
        return trace.timeline(self._trace_rid if self._trace_rid
                              is not None else self.id)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until terminal; returns generated ids [n] (np.int32).
        Raises TimeoutError if ``timeout`` elapses first."""
        with self._cv:
            if not self._cv.wait_for(
                    lambda: self._status in _TERMINAL, timeout):
                raise TimeoutError(
                    f"request {self.id} not finished within {timeout}s")
            status, err = self._status, self._error
            toks = np.asarray(self._tokens, np.int32)
        if status == FINISHED:
            return toks
        if status == CANCELLED:
            raise RequestCancelled(
                f"request {self.id} cancelled after {len(toks)} tokens")
        if status == EXPIRED:
            raise DeadlineExpired(
                f"request {self.id} deadline expired before admission")
        raise RequestFailed(str(err)) from err

    def stream(self, timeout: Optional[float] = None):
        """Yield generated token ids as they arrive; returns when the
        request reaches a terminal state (a CANCELLED stream simply ends
        after the partial tokens). ``timeout`` bounds each wait for the
        NEXT token, not the whole stream; expiry raises TimeoutError.
        EXPIRED/FAILED terminals re-raise like ``result()``.

        A raised TimeoutError ENDS the generator (Python generator
        semantics — a later ``next()`` returns StopIteration, it does
        not resume the wait): poll-style consumers should call
        ``stream()`` again, or read ``tokens_so_far()``/``status``
        directly the way the router's relay does."""
        sent = 0
        while True:
            with self._cv:
                if not self._cv.wait_for(
                        lambda: (len(self._tokens) > sent
                                 or self._status in _TERMINAL), timeout):
                    raise TimeoutError(
                        f"request {self.id}: no token within {timeout}s")
                chunk = self._tokens[sent:]
                status, err = self._status, self._error
            for t in chunk:
                yield t
            sent += len(chunk)
            if status in _TERMINAL and sent == len(self.tokens_so_far()):
                if status == EXPIRED:
                    raise DeadlineExpired(
                        f"request {self.id} deadline expired before "
                        "admission")
                if status == FAILED:
                    raise RequestFailed(str(err)) from err
                return

    __iter__ = stream

    # -- scheduler surface (single scheduler thread) -------------------------
    def _push(self, tokens) -> bool:
        """Append newly generated tokens; returns True when these are
        the request's FIRST tokens (TTFT edge)."""
        if not tokens:
            return False
        with self._cv:
            first = not self._tokens
            if first:
                self.first_token_ts = time.monotonic()
            self._tokens.extend(int(t) for t in tokens)
            self._cv.notify_all()
        if first and self._trace_ttft and trace.enabled():
            # the TTFT edge: serve_bench's trace-derived decomposition
            # splits submit->here into queue + prefill + gap shares
            trace.event("first_token",
                        rid=(self._trace_rid if self._trace_rid
                             is not None else self.id),
                        n=len(tokens))
        return first

    def _finish(self, status: str,
                error: Optional[BaseException] = None) -> None:
        with self._cv:
            if self._status in _TERMINAL:
                return
            self._status = status
            self._error = error
            self.finish_ts = time.monotonic()
            n = len(self._tokens)
            self._cv.notify_all()
        if trace.enabled():
            # one choke point covers EVERY terminal (finished /
            # cancelled / expired / failed) — the timeline's last event
            attrs = {"status": status, "n_tokens": n}
            if error is not None:
                attrs["error"] = repr(error)
            trace.event("finish",
                        rid=(self._trace_rid if self._trace_rid
                             is not None else self.id), **attrs)

    def _mark_running(self, engine_rid: int) -> None:
        with self._cv:
            self.engine_rid = engine_rid
            self._status = RUNNING
            if self.admit_ts is None:
                self.admit_ts = time.monotonic()


class RequestQueue:
    """Bounded priority queue of :class:`RequestHandle` (lower
    ``priority`` value = served first; FIFO within a priority).

    ``put`` applies BACKPRESSURE: a full queue raises :class:`QueueFull`
    (reject-with-reason — the 429 path) instead of growing without
    bound while the engine falls behind. Cancelled and deadline-expired
    entries are reaped at pop time and handed back to the scheduler for
    finalization — an expired request never admits.

    ``age_after_s`` enables PRIORITY AGING: a waiting request's
    effective priority improves by one level per ``age_after_s``
    seconds queued, so under sustained high-priority load a
    low-priority request is eventually served instead of starving
    forever. Aging is applied in :meth:`reap` (the scheduler calls it
    every inter-segment gap); FIFO order within an effective priority
    is preserved. ``None`` (default) keeps strict static priority.

    :meth:`penalize` pushes one tenant's entries into a PENALTY BAND
    (effective priority ``base + band``) until a deadline — the
    control plane's deprioritize-not-drop actuator for a tenant whose
    burn window fired. While the window is active, aging operates
    WITHIN the band: an aged penalized entry improves toward (but is
    clamped strictly above) its base priority, so a shed tenant's
    backlog can never age its way back to parity with healthy
    tenants before the window closes. Past the deadline the penalty
    clears and normal aging (from base) resumes.
    """

    def __init__(self, max_size: int,
                 age_after_s: Optional[float] = None):
        if max_size < 1:
            raise ValueError(f"max_size must be >= 1, got {max_size}")
        if age_after_s is not None and not age_after_s > 0:
            raise ValueError(
                f"age_after_s must be > 0 or None, got {age_after_s!r}")
        self.max_size = max_size
        self.age_after_s = age_after_s
        self._lock = threading.Lock()
        self._heap: List[Tuple[int, int, RequestHandle]] = []
        self._seq = itertools.count()
        # tenant -> (band, until_ts): active penalty windows
        self._penalty: dict = {}          # guarded-by: self._lock

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._heap)

    def put(self, handle: RequestHandle) -> None:
        with self._lock:
            if len(self._heap) >= self.max_size:
                raise QueueFull(self.max_size)
            eff = handle.priority
            pen = (self._penalty.get(handle.tenant)
                   if self._penalty else None)
            if pen is not None and time.monotonic() < pen[1]:
                eff += pen[0]
            heapq.heappush(self._heap,
                           (eff, next(self._seq), handle))

    def penalize(self, tenant: Optional[str], band: int,
                 until: float) -> None:
        """Deprioritize every queued (and future) entry of ``tenant``
        by ``band`` priority levels until ``until`` (absolute
        ``time.monotonic()``). Idempotent; re-penalizing extends or
        re-bases the window."""
        if tenant is None or band < 1:
            return
        with self._lock:
            self._penalty[tenant] = (int(band), float(until))
            changed = False
            for i, (eff, seq, h) in enumerate(self._heap):
                if h.tenant == tenant:
                    self._heap[i] = (h.priority + int(band), seq, h)
                    changed = True
            if changed:
                heapq.heapify(self._heap)

    def unpenalize(self, tenant: Optional[str]) -> None:
        """Clear a tenant's penalty window early and restore its
        queued entries to base priority (aging re-applies from there
        on the next :meth:`reap`)."""
        with self._lock:
            if self._penalty.pop(tenant, None) is None:
                return
            changed = False
            for i, (eff, seq, h) in enumerate(self._heap):
                if h.tenant == tenant and eff != h.priority:
                    self._heap[i] = (h.priority, seq, h)
                    changed = True
            if changed:
                heapq.heapify(self._heap)

    def reap(self, now: float) -> List[RequestHandle]:
        """Remove every cancelled/expired entry (anywhere in the queue,
        not just the head — a deep queue must not hold dead entries
        against ``max_size``) and return them for finalization. Also
        applies priority AGING (``age_after_s``): entries whose waited
        time crossed another aging step get their effective priority
        bumped and the heap re-ordered — penalized tenants age within
        their penalty band (clamped strictly above base priority)
        until the window expires."""
        with self._lock:
            expired_pen = [t for t, (_, until) in self._penalty.items()
                           if now >= until]
            if expired_pen:
                gone_pen = set(expired_pen)
                for t in expired_pen:
                    del self._penalty[t]
                changed = False
                for i, (eff, seq, h) in enumerate(self._heap):
                    if h.tenant in gone_pen and eff > h.priority:
                        self._heap[i] = (h.priority, seq, h)
                        changed = True
                if changed:
                    heapq.heapify(self._heap)
            if self.age_after_s is not None:
                aged = False
                for i, (eff, seq, h) in enumerate(self._heap):
                    credit = int((now - h.submit_ts) / self.age_after_s)
                    pen = self._penalty.get(h.tenant)
                    if pen is not None:
                        # age WITHIN the band: a shed tenant's entry
                        # improves but never reaches base parity while
                        # the window is open
                        new = max(h.priority + 1,
                                  h.priority + pen[0] - credit)
                    else:
                        new = h.priority - credit
                    if new < eff:
                        self._heap[i] = (new, seq, h)
                        aged = True
                if aged:
                    heapq.heapify(self._heap)
            dead = [h for _, _, h in self._heap
                    if h._cancel_requested
                    or (h.deadline is not None and now >= h.deadline)]
            if dead:
                gone = set(id(h) for h in dead)
                self._heap = [e for e in self._heap
                              if id(e[2]) not in gone]
                heapq.heapify(self._heap)
            return dead

    def pop_if(self, pred: Callable[[RequestHandle], bool]
               ) -> Optional[RequestHandle]:
        """Pop and return the head iff ``pred(head)`` — the scheduler's
        admission probe (no head-of-line bypass: requests admit in
        priority/FIFO order, like ``engine.serve()``'s pending list)."""
        with self._lock:
            if self._heap and pred(self._heap[0][2]):
                return heapq.heappop(self._heap)[2]
            return None

    def pop_admittable(self, fits: Callable[[RequestHandle], bool],
                       allowed: Callable[[RequestHandle], bool]
                       ) -> Optional[RequestHandle]:
        """Quota-aware admission pop: walk the queue in priority/FIFO
        order and pop the first entry that both ``fits`` (engine
        capacity) and is ``allowed`` (per-tenant quota). The scan STOPS
        at the first entry that does not fit — capacity keeps the
        no-head-of-line-bypass contract of :meth:`pop_if` — but entries
        deferred only by ``allowed`` are SKIPPED, so one tenant sitting
        over its quota defers its own work without starving every
        tenant queued behind it. O(n log n) over the waiting queue —
        bounded by ``max_size``, and only runs when quotas are
        configured."""
        with self._lock:
            for entry in sorted(self._heap):
                h = entry[2]
                if not fits(h):
                    return None
                if not allowed(h):
                    continue
                self._heap.remove(entry)
                heapq.heapify(self._heap)
                return h
            return None

    def drain_all(self) -> List[RequestHandle]:
        """Remove and return everything (shutdown path)."""
        with self._lock:
            out = [h for _, _, h in self._heap]
            self._heap = []
            return out
