"""Fleet-level fault tolerance: a health-aware replica router.

Everything below this module is ONE ``serving.Server`` on one engine —
survivable on its own (supervised recovery, pressure degradation, the
stall watchdog), but a wedged or restarting engine still stalls every
request in the process. THIS module is the scale-out half of the
millions-of-users shape: a :class:`Router` owns N replicas (in-process
:class:`~paddle_tpu.serving.scheduler.Server` instances built from a
:class:`ReplicaSpec` factory — the same seam later fronts remote HTTP
replicas) and turns "engine fault → backoff + replay" into "engine
fault → traffic shifts, users never notice":

- **health- and load-aware routing** — every pick reads each
  replica's lock-light load-snapshot fields (the same host-side reads
  ``Server.load()``/``/healthz`` report: status, queue depth, active
  slots, free pages; no HTTP, no device sync) and routes to the
  least-loaded replica whose status is ``ok`` — ``warming``,
  ``degraded``, ``failed``, draining and restarting replicas are
  excluded before a request ever touches them;
- **per-replica circuit breakers** — ``breaker_threshold`` consecutive
  submit/request failures OPEN the breaker (routing skips the replica
  — no more hammering a dying engine while its own watchdog is still
  counting down); after an exponential backoff the breaker goes
  HALF-OPEN and admits exactly ONE probe request: success closes it,
  failure re-opens with the backoff doubled;
- **failover replay** — a request whose replica dies or degrades
  mid-flight is resubmitted to a healthy replica as
  ``prompt + tokens already streamed`` with the budget reduced by what
  the client already has, so greedy failover is BITWISE-identical to
  an unfaulted run (the same bar as the in-engine replay of PR 4: a
  causal re-prefill of the same prefix). The router-level
  :class:`RouterHandle` keeps ONE stable request id and ONE
  uninterrupted ``stream()`` across replicas — the client never sees
  the seam. Bounded by ``max_failovers``; past it the request fails
  with :class:`FailoverBudgetExceeded` as its typed cause;
- **replica supervision** — a monitor thread restarts crashed/failed
  replicas from their spec with exponential backoff, bounded by
  ``max_replica_restarts`` per replica (past it the replica is DEAD
  and the fleet serves on what remains); :meth:`Router.drain` /
  :meth:`Router.restart_replica` / :meth:`Router.rolling_restart`
  drain ONE replica at a time while the rest serve — the fleet-level
  analogue of ``engine.reset_state()``;
- **one front door** — ``serve_http(router)`` proxies
  ``POST /generate`` (streaming preserved across failover — the ndjson
  stream rides the RouterHandle, not any one replica), aggregates
  fleet ``GET /healthz`` (per-replica states + breaker status +
  flight-dump paths via :meth:`Router.load`), and exports fleet
  ``/metrics`` (the monitor registry is process-wide — every replica's
  series plus the router's own land on one scrape endpoint).

Thread model: ``submit`` spawns one daemon PUMP thread per request
that owns that request's routing (pick replica → submit → relay the
inner stream → fail over); the monitor thread only restarts replicas
and never touches a live request; breaker/replica state transitions
all happen under the router lock. Replica ``Server`` objects keep
their own scheduler threads — the router never touches an engine.

What counts against a replica (breaker + failover): submit rejections
for REPLICA reasons (degraded / shutdown), an inner handle that FAILED
with an engine-side cause, a replica that cancelled the request on its
way down, and a replica observed ``degraded``/``failed`` mid-stream.
What does NOT: request-scoped verdicts that would fail identically on
any replica of the same spec — a prompt that can never fit
(``ValueError`` / :class:`PagePoolExhausted`) fails the request, not
the replica.
"""
from __future__ import annotations

import statistics
import threading
import time
from typing import List, Optional, Sequence, Union

import numpy as np

from .. import monitor
from .. import tracing as trace
from ..monitor import slo as _slo
from ..inference.generation import (GenerationConfig, PagePoolExhausted,
                                    _prompt_ids, _prompt_len)
from .control import ControlPolicy, ElasticController, max_burn
from .queue import (CANCELLED, EXPIRED, FAILED, FINISHED, _TERMINAL,
                    RequestFailed, RequestHandle, RequestRejected)
from .scheduler import PreemptionBudgetExceeded, Server

__all__ = ["Router", "ReplicaSpec", "RouterHandle",
           "FailoverBudgetExceeded", "FleetUnavailable",
           "BREAKER_CLOSED", "BREAKER_HALF_OPEN", "BREAKER_OPEN"]

# circuit-breaker states (the `paddle_tpu_router_breaker_state` gauge
# exports the numeric value; `load()` exports the name)
BREAKER_CLOSED = 0
BREAKER_HALF_OPEN = 1
BREAKER_OPEN = 2
_BREAKER_NAMES = {BREAKER_CLOSED: "closed",
                  BREAKER_HALF_OPEN: "half_open",
                  BREAKER_OPEN: "open"}


class FailoverBudgetExceeded(RuntimeError):
    """A request failed over more than ``max_failovers`` times: every
    replica it landed on died under it. Clients see it as the
    ``RequestFailed.__cause__`` of ``result()`` — a typed terminal
    failure, not an endless migration."""


class FleetUnavailable(RuntimeError):
    """No replica can ever serve this request again: every replica is
    permanently dead (its ``max_replica_restarts`` budget exhausted).
    Distinct from a transient all-busy/all-restarting state, which the
    router WAITS through."""


class ReplicaSpec:
    """Recipe for building one replica: an ``engine_factory`` callable
    (returns a fresh engine each call) plus the ``Server(...)``
    keyword arguments every build uses. The factory must build a
    fresh MODEL per replica too — replica scheduler threads trace jit
    programs concurrently, and the engines' ``substituted_state``
    parameter swap is per-model, not thread-safe across sharers; seed
    the construction (``paddle.seed(k)`` before each build) and the
    deterministic init gives every replica bitwise-identical weights,
    which is what makes greedy failover exact. The same seam later
    fronts remote HTTP replicas: anything with
    ``build() -> Server-shaped object`` routes.

    Engine knobs mirror through ``server_kwargs`` — e.g.
    ``server_kwargs={"kv_dtype": "int8"}`` builds every replica on
    quantized KV pages (greedy failover replay stays exact across the
    fleet: identical weights + identical quantization make every
    replica's bounded numerics the SAME numerics).

    ``devices`` pins THIS replica to a device subset (ints index
    ``jax.devices()``; device objects pass through): the factory is
    then called as ``engine_factory(devices)`` and owns forwarding
    them (a tensor-parallel engine passes ``tp_devices=devices``), so
    an N-replica × TP-k fleet partitions one slice — scale-up per
    replica × scale-out across replicas in one topology — instead of
    every replica claiming device 0::

        devs = jax.devices()
        specs = [ReplicaSpec(make_tp_engine, devices=devs[2*i:2*i+2])
                 for i in range(4)]        # 4 replicas × TP=2 on 8 chips
        router = Router(specs)
    """

    def __init__(self, engine_factory, server_kwargs: Optional[dict]
                 = None, devices: Optional[Sequence] = None):
        if not callable(engine_factory):
            raise ValueError("engine_factory must be callable "
                             f"(got {engine_factory!r})")
        self.engine_factory = engine_factory
        self.server_kwargs = dict(server_kwargs or {})
        self.devices = None if devices is None else list(devices)
        if self.devices is not None and not self.devices:
            raise ValueError("devices must be a non-empty sequence "
                             "or None (any device)")

    def build(self) -> Server:
        """Build (and start) one fresh replica Server. With ``devices``
        pinned the factory is called with them — every supervised
        rebuild of this replica lands back on ITS device subset."""
        eng = (self.engine_factory(self.devices)
               if self.devices is not None else self.engine_factory())
        return Server(eng, **self.server_kwargs)


class RouterHandle(RequestHandle):
    """One router-level request: the SAME client surface as
    :class:`RequestHandle` (``result()`` / ``stream()`` / ``cancel()``
    / ``timeline()``), but the request id, the token stream, and the
    trace timeline are all ROUTER-scoped — they survive any number of
    replica failovers underneath. ``replica`` is the index currently
    (or last) serving it; ``failovers`` counts migrations."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._inner: Optional[RequestHandle] = None   # current replica
        #                                               handle (pump)
        self._failovers = 0
        self._ever_admitted = False   # once True the admission
        #                               deadline no longer applies to
        #                               resubmits (it was met once —
        #                               same contract as PR 4 replay)
        self.replica: Optional[int] = None

    @property
    def failovers(self) -> int:
        return self._failovers

    def cancel(self) -> None:
        """Cancel the request (idempotent): flags the router pump AND
        forwards to whichever replica currently runs it, so the slot
        (and pages) there reclaim at its next gap."""
        super().cancel()            # sets the flag + wakes the pump
        inner = self._inner
        if inner is not None:
            inner.cancel()


class _Replica:
    """Router-side record for one replica slot (all mutable state is
    guarded by the router lock)."""

    __slots__ = ("index", "spec", "server", "breaker", "failures",
                 "opens", "open_until", "backoff_mult", "probing",
                 "restarts", "deliberate_restarts", "restart_at",
                 "draining", "dead", "slow", "scaled_down")

    def __init__(self, index: int, spec: ReplicaSpec, server):
        self.index = index
        self.spec = spec
        self.server = server
        self.breaker = BREAKER_CLOSED
        self.failures = 0          # consecutive failures (reset on
        #                            success)
        self.opens = 0             # lifetime breaker-open count
        self.open_until = 0.0
        self.backoff_mult = 1.0    # doubles per consecutive open,
        #                            resets when the breaker closes
        self.probing = False       # half-open: one probe in flight
        self.restarts = 0          # supervised restarts consumed
        #                            (the max_replica_restarts budget)
        self.deliberate_restarts = 0   # rolling-restart rebuilds
        #                                (budget-exempt: operator-run)
        self.restart_at: Optional[float] = None   # backoff deadline
        #                            while a restart is pending
        self.draining = False      # deliberately excluded (drain /
        #                            rolling restart)
        self.dead = False          # restart budget exhausted
        self.slow = False          # skew detector verdict: rolling
        #                            TPOT p50 > skew_factor x fleet
        #                            median — ALIVE but lagging; routed
        #                            last, never walled off (slow !=
        #                            open breaker)
        self.scaled_down = False   # elastically parked: drained +
        #                            shut down by the autoscaler, slot
        #                            kept so a scale-up revives it
        #                            from ITS spec (and device subset)

    # both helpers mutate breaker/supervision state: caller holds the
    # router lock
    def reset_health(self, server=None) -> None:
        """Back to a clean routable state (fresh build / deliberate
        restart): failures forgotten, breaker closed, no probe, no
        pending restart."""
        if server is not None:
            self.server = server
        self.failures = 0
        self.breaker = BREAKER_CLOSED
        self.backoff_mult = 1.0
        self.probing = False
        self.restart_at = None
        self.dead = False
        self.slow = False     # a fresh server has a fresh engine: the
        #                       old skew verdict is stale evidence

    def mark_dead(self) -> None:
        """Restart budget exhausted: permanently out of rotation,
        breaker pinned open."""
        self.dead = True
        self.breaker = BREAKER_OPEN
        self.open_until = float("inf")
        self.restart_at = None
        self.slow = False     # dead outranks slow; the gauge reads 0


class Router:
    """Front tier spreading requests over N replica Servers.

    Usage::

        model = LlamaForCausalLM(cfg)          # ONE model, N engines
        spec = ReplicaSpec(
            lambda: PagedContinuousBatchingEngine(
                model, max_batch=4, num_pages=64, page_size=16,
                max_pages=32),
            server_kwargs={"segment_steps": 8})
        router = Router(spec, replicas=3)
        h = router.submit(prompt_ids, GenerationConfig(max_new_tokens=64))
        for tok in h.stream():     # uninterrupted even if a replica dies
            ...
        router.shutdown()

    Knobs:

    - ``max_failovers`` — replica migrations any ONE request may
      survive; past it: :class:`FailoverBudgetExceeded`;
    - ``breaker_threshold`` / ``breaker_backoff_s`` /
      ``breaker_backoff_max_s`` — consecutive failures before a
      replica's breaker OPENs, and the (exponential, capped) backoff
      before its half-open probe;
    - ``max_replica_restarts`` / ``replica_backoff_s`` /
      ``replica_backoff_max_s`` — supervised restarts per replica and
      their exponential backoff; past the budget the replica is DEAD;
    - ``monitor_interval_s`` — supervisor poll period (detection
      latency for a crashed replica is at most one period + the
      backoff);
    - ``degraded_poll_s`` — how often a pump waiting on a silent
      replica re-checks its health (a replica observed ``degraded`` /
      ``failed`` mid-stream is abandoned and the request fails over);
    - ``retry_wait_s`` — pump back-off while NO replica is routable
      (all warming/restarting/open): the request waits instead of
      failing, bounded by its own deadline and by the fleet going
      permanently dead;
    - ``skew_factor`` / ``skew_min_requests`` / ``skew_interval_s`` —
      the SLOW-REPLICA skew detector: every ``skew_interval_s`` the
      monitor thread compares each replica's rolling-window TPOT p50
      (>= ``skew_min_requests`` observations required) against the
      median of its PEERS' p50s (leave-one-out); above
      ``skew_factor``× that median the replica flips
      SLOW — deprioritized in routing (scored behind every non-slow
      candidate) but still routable, surfaced in ``load()`` /
      ``GET /stats``, flight-recorder dump on the flip. Slow is the
      state breakers cannot see: the replica answers everything,
      just late;
    - ``elastic`` / ``elastic_interval_s`` — ELASTIC FLEET sizing
      (``serving.control``): pass a :class:`ControlPolicy` (or a
      pre-built :class:`ElasticController`) and the supervisor
      thread grows/shrinks the serving replica count from queue
      depth + burn rate, between 1 and ``len(specs)``. Scale-down
      DRAINS the least-loaded replica (in-flight work always
      finishes — the rolling-restart bar) and parks its slot;
      scale-up revives a parked slot from its own spec. Decisions
      are streak-gated and cooldown-rate-limited (flap-resistant);
      :meth:`scale_to` is the deliberate operator override.
    """

    def __init__(self,
                 specs: Union[ReplicaSpec, Sequence[ReplicaSpec]],
                 replicas: Optional[int] = None, *,
                 max_failovers: int = 2,
                 breaker_threshold: int = 3,
                 breaker_backoff_s: float = 0.25,
                 breaker_backoff_max_s: float = 8.0,
                 max_replica_restarts: int = 3,
                 replica_backoff_s: float = 0.05,
                 replica_backoff_max_s: float = 2.0,
                 monitor_interval_s: float = 0.05,
                 degraded_poll_s: float = 0.25,
                 retry_wait_s: float = 0.02,
                 tight_headroom_s: float = 0.25,
                 skew_factor: float = 2.0,
                 skew_min_requests: int = 5,
                 skew_interval_s: float = 1.0,
                 elastic=None,
                 elastic_interval_s: float = 0.5,
                 start: bool = True):
        if isinstance(specs, ReplicaSpec):
            n = 1 if replicas is None else replicas
            if n < 1:
                raise ValueError(f"replicas must be >= 1, got {n}")
            specs = [specs] * n
        else:
            specs = list(specs)
            if replicas is not None and replicas != len(specs):
                raise ValueError(
                    f"replicas={replicas} contradicts the {len(specs)} "
                    "specs passed; give one spec + replicas=N, or a "
                    "list of specs")
            if not specs:
                raise ValueError("need at least one ReplicaSpec")
        if max_failovers < 0 or max_replica_restarts < 0:
            raise ValueError(
                "max_failovers/max_replica_restarts must be >= 0")
        if breaker_threshold < 1:
            raise ValueError(
                f"breaker_threshold must be >= 1, got "
                f"{breaker_threshold}")
        for name, v in (("breaker_backoff_s", breaker_backoff_s),
                        ("replica_backoff_s", replica_backoff_s),
                        ("monitor_interval_s", monitor_interval_s),
                        ("degraded_poll_s", degraded_poll_s),
                        ("retry_wait_s", retry_wait_s),
                        ("skew_interval_s", skew_interval_s)):
            if not v > 0:
                raise ValueError(f"{name} must be > 0, got {v!r}")
        if not skew_factor > 1.0:
            # factor <= 1 would flag roughly half a healthy,
            # noise-jittered fleet slow at every check
            raise ValueError(
                f"skew_factor must be > 1.0, got {skew_factor!r}")
        if skew_min_requests < 1:
            raise ValueError(
                f"skew_min_requests must be >= 1, got "
                f"{skew_min_requests!r}")
        if not elastic_interval_s > 0:
            raise ValueError(
                f"elastic_interval_s must be > 0, got "
                f"{elastic_interval_s!r}")
        # elastic fleet sizing (serving.control.ElasticController):
        # the supervisor thread grows/shrinks the ROUTABLE replica
        # count between 1 and the spec list's length — scale-down
        # DRAINS (PR 9 machinery: in-flight work always finishes, the
        # slot parks scaled_down), scale-up revives a parked slot from
        # ITS spec, so devices=... partitions are honoured on the way
        # back. Pass a ControlPolicy (wrapped here) or a pre-built
        # ElasticController; None = fixed fleet.
        if isinstance(elastic, ControlPolicy):
            elastic = ElasticController(elastic, min_replicas=1,
                                        max_replicas=len(specs))
        elif elastic is not None and not isinstance(elastic,
                                                    ElasticController):
            raise ValueError(
                f"elastic must be a ControlPolicy, an "
                f"ElasticController, or None, got {elastic!r}")
        self._elastic = elastic
        self.elastic_interval_s = elastic_interval_s
        self.max_failovers = max_failovers
        self.breaker_threshold = breaker_threshold
        self.breaker_backoff_s = breaker_backoff_s
        self.breaker_backoff_max_s = breaker_backoff_max_s
        self.max_replica_restarts = max_replica_restarts
        self.replica_backoff_s = replica_backoff_s
        self.replica_backoff_max_s = replica_backoff_max_s
        self.monitor_interval_s = monitor_interval_s
        self.degraded_poll_s = degraded_poll_s
        self.retry_wait_s = retry_wait_s
        # SLO-headroom tiebreak (ROADMAP 2c): below this remaining
        # deadline, failover/route scoring drops the adapter-affinity
        # term — a warm LoRA bank row saves milliseconds, and a
        # request this close to its deadline needs the least-loaded
        # replica, not the warmest one
        self.tight_headroom_s = tight_headroom_s
        # Retry-After honor windows: replica index -> monotonic time
        # before which _acquire deprioritizes it (it told us when to
        # come back — believe it, unless nobody else is routable)
        self._reject_until = {}
        self.skew_factor = skew_factor
        self.skew_min_requests = skew_min_requests
        self.skew_interval_s = skew_interval_s
        self.monitor_router = monitor.instance_label("router")
        # one spec shared by every replica: a capacity verdict
        # (ValueError / PagePoolExhausted) from one replica holds for
        # all of them; a heterogeneous list must try each spec before
        # declaring a request unservable
        self._homogeneous = all(s is specs[0] for s in specs)
        self._lock = threading.Lock()
        self._idle_cv = threading.Condition()
        self._next_id = 0                 # guarded-by: self._lock
        self._handles: set = set()        # guarded-by: self._lock
        #                                   live RouterHandles (pumps
        #                                   remove on terminal)
        self._failovers_total = 0         # guarded-by: self._lock
        self._draining = False            # guarded-by: self._lock
        self._stopping = False            # guarded-by: self._lock
        self._flight_dumps = []           # guarded-by: self._lock
        #                                   router-level flight-recorder
        #                                   dump paths (skew flips)
        self._stop_evt = threading.Event()
        # building a replica compiles nothing by itself (Server warmup
        # is a spec knob) but does allocate device state — build them
        # serially, before any thread exists, so a constructor failure
        # leaves nothing half-started
        self._replicas: List[_Replica] = []
        try:
            for i, spec in enumerate(specs):
                if not isinstance(spec, ReplicaSpec):
                    raise ValueError(
                        f"specs[{i}] is not a ReplicaSpec: {spec!r}")
                self._replicas.append(_Replica(i, spec, spec.build()))
        except BaseException:
            for rep in self._replicas:
                try:
                    rep.server.shutdown(drain=False, timeout=5.0)
                except Exception:
                    pass
            raise
        for rep in self._replicas:
            self._breaker_metric(rep)
            self._slow_metric(rep)
        self._replicas_metric()
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name=f"paddle_tpu-router-monitor-{self.monitor_router}")
        if start:
            self._monitor_thread.start()

    # -- client surface ------------------------------------------------------
    def submit(self, prompt, cfg: Optional[GenerationConfig] = None,
               priority: int = 0,
               timeout_s: Optional[float] = None,
               tenant: Optional[str] = None) -> RouterHandle:
        """Route one request into the fleet; returns its
        :class:`RouterHandle`. Raises
        :class:`~paddle_tpu.serving.queue.RequestRejected` (reason
        ``draining`` / ``shutdown`` / ``unavailable`` — the last only
        when EVERY replica is permanently dead), ValueError for a
        prompt that can never fit the replica engines. A fleet that is
        merely busy/restarting ACCEPTS the request — the pump waits
        for a routable replica (bounded by ``timeout_s``)."""
        cfg = cfg or GenerationConfig()
        plen = _prompt_len(prompt)
        with self._lock:
            if self._stopping:
                raise RequestRejected("shutdown",
                                      "router is shut down")
            if self._draining:
                raise RequestRejected(
                    "draining",
                    "router is draining; not accepting new requests")
            if all(rep.dead for rep in self._replicas):
                raise RequestRejected(
                    "unavailable",
                    "every replica is permanently dead "
                    "(max_replica_restarts exhausted fleet-wide)")
            # same-spec replicas share max_len: fail a can-never-fit
            # prompt fast, before a pump cycles it through the fleet
            max_len = max(getattr(rep.server.engine, "max_len", 1 << 30)
                          for rep in self._replicas if not rep.dead)
            if plen + cfg.max_new_tokens > max_len:
                raise ValueError(
                    f"prompt({plen}) + max_new_tokens"
                    f"({cfg.max_new_tokens}) exceeds replica "
                    f"max_len({max_len})")
            deadline = (None if timeout_s is None
                        else time.monotonic() + timeout_s)
            h = RouterHandle(self._next_id, prompt, plen, cfg,
                             priority, deadline, tenant=tenant)
            h._trace_rid = f"{self.monitor_router}:{h.id}"
            self._next_id += 1
            self._handles.add(h)
        pump = threading.Thread(
            target=self._run_request, args=(h,), daemon=True,
            name=f"paddle_tpu-router-pump-{self.monitor_router}-{h.id}")
        pump.start()
        return h

    def request_timeline(self, request_id: int):
        """One router request's ordered trace timeline by its public id
        — spans BOTH replicas across a failover (the router stamps its
        stable rid into every replica submit). Same contract as
        ``RequestHandle.timeline()``."""
        return trace.timeline(f"{self.monitor_router}:{request_id}")

    def num_active(self) -> int:
        """Router-level in-flight requests (pumps not yet terminal)."""
        with self._lock:
            return len(self._handles)

    @property
    def failovers(self) -> int:
        """Total failovers performed over the router's lifetime."""
        with self._lock:
            return self._failovers_total

    @property
    def status(self) -> str:
        """``ok`` (every replica routable) / ``degraded`` (some — or
        transiently all — replicas down while the fleet lives:
        restarting/warming/breaker-open replicas come back) /
        ``failed`` (every replica PERMANENTLY dead — restart budgets
        exhausted, nothing will ever route again) / ``draining`` /
        ``stopped``. The HTTP 200/503 verdict is the separate
        ``load()["healthy"]`` flag: >= 1 replica routable right
        now."""
        return self.load()["status"]

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until every (non-dead) replica finished warmup."""
        end = (None if timeout is None
               else time.monotonic() + timeout)
        for rep in list(self._replicas):
            t = (None if end is None
                 else max(0.0, end - time.monotonic()))
            if not rep.server.wait_ready(t):
                return False
        return True

    def load(self) -> dict:  # lint: hot-path
        """The FLEET health/load snapshot — what ``/healthz`` serves
        (the router quacks like a Server to ``serve_http``): top-level
        ``{"status", "healthy", "router", "replicas": [...],
        "queue_depth", "active_requests", "free_slots",
        "inflight_requests", "failovers", "breaker_opens"}`` with one
        entry per replica carrying its state (``dead`` / ``restarting``
        / ``draining`` / the Server's own status), its breaker
        ``{"state", "failures", "opens"}``, supervised ``restarts``,
        its ``Server.load()`` numbers, and its flight-recorder dump
        paths. ``healthy`` (the HTTP 200 verdict) is ">= 1 routable
        replica and not stopping" — one dead replica degrades the
        fleet, it does not fail it."""
        with self._lock:
            reps = list(self._replicas)
            stopping = self._stopping
            draining = self._draining
            inflight = len(self._handles)
            failovers = self._failovers_total
        now = time.monotonic()
        entries = []
        routable = agg_q = agg_a = agg_f = opens = 0
        for rep in reps:
            try:
                snap = rep.server.load()
            except Exception:   # mid-swap / torn replica: skip numbers
                snap = {"status": "unknown"}
            if rep.dead:
                state = "dead"
            elif rep.scaled_down:
                state = "scaled_down"
            elif rep.restart_at is not None:
                state = "restarting"
            elif rep.draining:
                state = "draining"
            else:
                state = snap["status"]
            breaker = rep.breaker
            if breaker == BREAKER_OPEN and now >= rep.open_until:
                breaker = BREAKER_HALF_OPEN   # display-only: the next
                #                               pick makes it official
            entry = {
                "replica": rep.index,
                "status": state,
                "slow": rep.slow,
                "breaker": {"state": _BREAKER_NAMES[breaker],
                            "failures": rep.failures,
                            "opens": rep.opens},
                "restarts": rep.restarts,
                "deliberate_restarts": rep.deliberate_restarts,
                "load": {k: snap[k] for k in
                         ("queue_depth", "active_requests",
                          "free_slots", "free_pages", "occupancy")
                         if k in snap},
            }
            if "tp" in snap:
                # mesh shape per replica: fleet /healthz shows how a
                # scale-up (TP) x scale-out (replicas) topology
                # partitions the slice
                entry["tp"] = snap["tp"]
            dumps = []
            try:
                dumps = rep.server.flight_dumps
            except Exception:
                pass
            if dumps:
                entry["flight_dumps"] = dumps
            entries.append(entry)
            opens += rep.opens
            if not rep.dead:
                # queued/active work is real wherever it sits (a
                # draining replica still finishes its requests) — but
                # a dead server's finalizer reclaimed everything, so
                # counting it would be phantom load
                agg_q += snap.get("queue_depth", 0)
                agg_a += snap.get("active_requests", 0)
            if state == "ok" and breaker != BREAKER_OPEN:
                routable += 1
                # advertised capacity is ROUTABLE capacity only: a
                # dead/draining/restarting/walled-off replica's free
                # slots can't serve new traffic, and an autoscaler
                # reading the aggregate must not see them
                agg_f += snap.get("free_slots", 0)
        if stopping:
            status = "stopped"
        elif all(r.dead for r in reps):
            status = "failed"
        elif draining:
            status = "draining"
        elif routable == sum(1 for r in reps if not r.scaled_down):
            # a deliberately parked (scaled-down) replica is capacity
            # the autoscaler CHOSE not to run — the fleet it sized is
            # fully routable, so it reads ok, not degraded
            status = "ok"
        else:
            # routable == 0 but not all dead reads "degraded", not
            # "failed": restarting/warming/breaker-open replicas come
            # back on their own (an all-warming fleet at boot is not
            # an outage) — `healthy` carries the take-no-traffic fact
            status = "degraded"
        healthy = (not stopping and routable >= 1
                   and not all(r.dead for r in reps))
        out = {"status": status, "healthy": healthy,
               "router": self.monitor_router, "replicas": entries,
               "queue_depth": agg_q, "active_requests": agg_a,
               "free_slots": agg_f, "inflight_requests": inflight,
               "failovers": failovers, "breaker_opens": opens,
               "slow_replicas": [e["replica"] for e in entries
                                 if e.get("slow")],
               "scaled_down": [r.index for r in reps
                               if r.scaled_down]}
        with self._lock:
            if self._flight_dumps:
                out["flight_dump"] = self._flight_dumps[-1]
        return out

    def stats(self) -> dict:
        """The fleet SLO rollup — ``GET /stats``. EXACT by
        construction: per-(metric, tenant) latency percentiles come
        from MERGING every live replica's fixed-log-bucket digests
        (identical bucketization → elementwise counter add → the
        merged digest IS the digest of the concatenated request
        streams), and per-tenant goodput/burn come from SUMMING the
        replicas' met/missed counters — never from averaging replica
        percentiles or rates, which is the classic fleet-dashboard
        lie this endpoint exists to replace. Shape::

            {"router", "policy", "window_s",
             "tenants": {tenant: {requests, met, missed, failed,
                                  goodput, burn_fast, burn_slow,
                                  tokens, kv_page_seconds}},
             "metrics": {metric: {tenant: {count, mean, p50, p90,
                                           p99, max},
                                  "*": <exact all-tenant merge>}},
             "replicas": [{replica, slow, dead, tpot_p50_s,
                           metrics: <per-replica percentiles>}],
             "skew": {"factor", "min_requests",
                      "slow_replicas": [...]}}

        The per-replica ``metrics`` blocks are what the fleet-vs-
        replica comparison in ``tools/monitor_report.py --slo`` reads
        — the gap between a replica's p99 and the fleet's is the skew
        detector's story told in percentiles."""
        with self._lock:
            reps = list(self._replicas)
        shards, entries = [], []
        for rep in reps:
            entry = {"replica": rep.index, "slow": rep.slow,
                     "dead": rep.dead}
            tracker = getattr(rep.server, "slo", None)
            if tracker is not None and not rep.dead:
                try:
                    shard = tracker.digests_dict()
                    entry["tpot_p50_s"] = tracker.rolling_tpot_p50()
                    entry["metrics"] = tracker.percentiles()
                except Exception:   # mid-swap replica: skip its shard
                    shard = None
                if shard is not None:
                    shards.append(shard)
            entries.append(entry)
        out = _slo.fleet_rollup(shards)
        out["router"] = self.monitor_router
        out["replicas"] = entries
        out["skew"] = {"factor": self.skew_factor,
                       "min_requests": self.skew_min_requests,
                       "slow_replicas": [e["replica"] for e in entries
                                         if e.get("slow")]}
        return out

    def profile(self, top_k: Optional[int] = None) -> dict:
        """The fleet program-ledger rollup — ``GET /profile``. EXACT
        the same way :meth:`stats` is: every live replica's
        :meth:`Server.profile` shard is merged per program id — digest
        buckets add elementwise (one fixed bucketization), dispatch/
        compile counters sum, cost analysis comes from the first shard
        that has it — never an average of per-replica MFUs. Dead and
        mid-swap replicas are skipped, same as the SLO rollup."""
        from ..monitor import ledger as _ledger

        with self._lock:
            reps = list(self._replicas)
        shards = []
        for rep in reps:
            if rep.dead:
                continue
            fn = getattr(rep.server, "profile", None)
            if fn is None:
                continue
            try:
                shards.append(fn())
            except Exception:   # mid-swap replica: skip its shard
                pass
        out = _ledger.merge_profiles(shards, top_k=top_k)
        out["router"] = self.monitor_router
        out["replicas"] = len(shards)
        return out

    # -- drain / rolling restart ---------------------------------------------
    def drain(self, index: Optional[int] = None,
              timeout: Optional[float] = None) -> bool:
        """``drain()`` — FLEET drain: stop accepting new submissions
        and wait for every in-flight router handle to reach a terminal
        state (replays and failovers included). ``drain(i)`` — drain
        ONE replica while the rest serve: exclude it from routing,
        then ``Server.drain`` it (its queued + active requests run to
        completion). A drained replica stays excluded until
        :meth:`restart_replica` rebuilds it — ``Server.drain`` is
        one-way, which is exactly the rolling-restart contract.
        Returns True when everything finished in time."""
        if index is not None:
            rep = self._replicas[index]
            with self._lock:
                rep.draining = True
            if trace.enabled():
                trace.event("replica.drain", replica=index,
                            router=self.monitor_router)
            return rep.server.drain(timeout)
        with self._lock:
            self._draining = True
        with self._idle_cv:
            # lint: allow-unlocked(atomic emptiness probe inside the
            # cv predicate — re-evaluated on every notify; pumps hold
            # _lock for the actual mutation and notify after)
            return self._idle_cv.wait_for(
                lambda: not self._handles, timeout)

    def restart_replica(self, index: int,
                        timeout: Optional[float] = None,
                        drain: bool = True) -> bool:
        """Deliberately restart ONE replica: drain it (in-flight work
        finishes; routing already excludes it), shut the old Server
        down, build a fresh one from the spec, wait for its warmup,
        and put it back in rotation with a CLOSED breaker. Returns the
        drain verdict (True = nothing was cut short). The supervisor
        thread ignores replicas mid-deliberate-restart, so the two
        never fight over one slot."""
        rep = self._replicas[index]
        with self._lock:
            # fence the supervisor off this slot for the WHOLE
            # deliberate restart — with drain=False nothing else would
            # set the flag, and a supervisor tick observing the old
            # server "stopped" mid-swap would burn a supervised-restart
            # budget unit and race-build a duplicate server
            rep.draining = True
        drained = self.drain(index, timeout) if drain else True
        old = rep.server
        try:
            old.shutdown(drain=False, timeout=timeout)
        except Exception:
            pass
        new = rep.spec.build()
        new.wait_ready(timeout)
        with self._lock:
            # the operator's restart WINS a race against a concurrent
            # supervisor install (possible with drain=False, where the
            # draining flag never fenced the supervisor off) — but the
            # interloper server must be stopped, not silently leaked
            interloper = rep.server if rep.server is not old else None
            rep.reset_health(server=new)
            rep.draining = False
            rep.deliberate_restarts += 1
        if interloper is not None:
            try:
                interloper.shutdown(drain=False, timeout=5.0)
            except Exception:
                pass
        self._breaker_metric(rep)
        # reset_health cleared rep.slow OUT OF BAND (a fresh engine's
        # skew verdict starts over) — the gauge must follow, or it
        # exports a phantom slow=1 the next _check_skew never corrects
        # (it only writes on a flag CHANGE)
        self._slow_metric(rep)
        if monitor.enabled():
            self._restarts_counter().labels(
                router=self.monitor_router,
                replica=str(index)).inc()
        if trace.enabled():
            trace.event("replica.restart", replica=index,
                        deliberate=True, router=self.monitor_router)
        return drained

    def rolling_restart(self, timeout: Optional[float] = None) -> bool:
        """Restart every replica ONE AT A TIME (drain → rebuild →
        ready → next) while the rest keep serving — config/weight
        rollouts without a maintenance window. Returns True when every
        per-replica drain completed cleanly."""
        if trace.enabled():
            trace.event("rolling_restart", router=self.monitor_router,
                        replicas=len(self._replicas))
        ok = True
        for i in range(len(self._replicas)):
            ok = self.restart_replica(i, timeout) and ok
        return ok

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop the fleet: optionally drain (bounded by ``timeout``),
        then stop the supervisor, shut every replica down (their
        finalizers cancel whatever remains — pumps observe it and
        finish their handles), and retire the router's metric
        series."""
        if drain:
            self.drain(timeout=timeout)
        with self._lock:
            self._stopping = True
            self._draining = True
        self._stop_evt.set()
        if self._monitor_thread.is_alive():
            self._monitor_thread.join(timeout=5.0)
        for rep in self._replicas:
            try:
                rep.server.shutdown(drain=False, timeout=timeout)
            except Exception:
                pass
            # the router built these engines (engine_factory), so it
            # closes them: per-engine monitor series AND the program
            # ledger rows they own retire here — Router.shutdown()
            # leaves zero {program=...} series behind
            try:
                eng = getattr(rep.server, "engine", None)
                if eng is not None:
                    eng.close()
            except Exception:
                pass
        # pumps unwind on their cancelled/failed inner handles; give
        # them a bounded window so no handle is left non-terminal
        with self._idle_cv:
            # lint: allow-unlocked(same atomic cv-predicate probe as
            # drain(); the terminal sweep below re-reads under _lock)
            self._idle_cv.wait_for(lambda: not self._handles, 10.0)
        with self._lock:
            leftovers = list(self._handles)
        for h in leftovers:   # belt and braces: a wedged pump must not
            #                   strand its client
            h._finish(CANCELLED)
        for name in ("paddle_tpu_router_requests_total",
                     "paddle_tpu_router_failovers_total",
                     "paddle_tpu_router_breaker_state",
                     "paddle_tpu_router_replica_restarts_total",
                     "paddle_tpu_router_replica_slow",
                     # elastic fleet (PR 19): the replicas gauge would
                     # export a stale fleet size forever
                     "paddle_tpu_router_scale_events_total",
                     "paddle_tpu_router_replicas"):
            try:
                monitor.remove_series(name, router=self.monitor_router)
            except Exception:
                pass

    def close(self) -> None:
        self.shutdown(drain=False)

    # -- monitor helpers -----------------------------------------------------
    @staticmethod
    def _requests_counter():
        return monitor.counter(
            "paddle_tpu_router_requests_total",
            "router-level requests by replica and outcome "
            "(completed/failed/cancelled/expired/failover — one "
            "terminal count per request plus one per migration; "
            "per-attempt backpressure lives on the replicas' "
            "serving_requests_total{event=rejected_*})",
            ("router", "replica", "outcome"))

    @staticmethod
    def _failovers_counter():
        return monitor.counter(
            "paddle_tpu_router_failovers_total",
            "requests migrated to another replica after their replica "
            "died or degraded mid-flight", ("router",))

    @staticmethod
    def _breaker_gauge():
        return monitor.gauge(
            "paddle_tpu_router_breaker_state",
            "per-replica circuit breaker: 0 closed, 1 half-open, "
            "2 open", ("router", "replica"))

    @staticmethod
    def _restarts_counter():
        return monitor.counter(
            "paddle_tpu_router_replica_restarts_total",
            "replica Servers rebuilt from their spec (supervised "
            "crash recovery + deliberate rolling restarts)",
            ("router", "replica"))

    @staticmethod
    def _scale_counter():
        return monitor.counter(
            "paddle_tpu_router_scale_events_total",
            "elastic fleet scale decisions applied "
            "(action=up revives a parked slot, action=down drains "
            "one replica and parks it)", ("router", "action"))

    @staticmethod
    def _replicas_gauge():
        return monitor.gauge(
            "paddle_tpu_router_replicas",
            "replica slots currently in the serving fleet (not "
            "parked by the autoscaler, not permanently dead)",
            ("router",))

    @staticmethod
    def _slow_gauge():
        return monitor.gauge(
            "paddle_tpu_router_replica_slow",
            "skew-detector verdict: 1 while the replica's rolling "
            "TPOT p50 exceeds the fleet median by skew_factor "
            "(slow-but-alive — deprioritized in routing, breaker "
            "untouched), else 0", ("router", "replica"))

    def _count(self, outcome: str, replica) -> None:
        if monitor.enabled():
            self._requests_counter().labels(
                router=self.monitor_router,
                replica=("none" if replica is None else str(replica)),
                outcome=outcome).inc()

    def _breaker_metric(self, rep: _Replica) -> None:
        if monitor.enabled():
            self._breaker_gauge().labels(
                router=self.monitor_router,
                replica=str(rep.index)).set(rep.breaker)

    def _slow_metric(self, rep: _Replica) -> None:
        if monitor.enabled():
            self._slow_gauge().labels(
                router=self.monitor_router,
                replica=str(rep.index)).set(int(rep.slow))

    def _replicas_metric(self) -> None:
        if monitor.enabled():
            with self._lock:
                n = sum(1 for r in self._replicas
                        if not r.dead and not r.scaled_down)
            self._replicas_gauge().labels(
                router=self.monitor_router).set(n)

    def _flight_dump(self, reason: str):
        """Router-level flight-recorder dump (no-op while tracing is
        off), mirroring the Server's: the skew detector fires one when
        a replica flips SLOW — a lagging-but-alive replica is exactly
        the postmortem the breakers never capture (they only see
        failures). Never raises."""
        if not trace.enabled():
            return None
        try:
            path = trace.dump(reason)
        except Exception:
            return None
        if path is not None:
            with self._lock:
                self._flight_dumps.append(path)
        return path

    @property
    def flight_dumps(self):
        """Router-level flight-recorder dump paths (newest last)."""
        with self._lock:
            return list(self._flight_dumps)

    # -- breaker transitions (router lock) -----------------------------------
    def _replica_failure(self, rep: _Replica, srv, err,
                         probe: bool) -> None:
        """Record one replica-attributed failure: bump the consecutive
        count, OPEN the breaker at the threshold (or immediately on a
        failed half-open probe, with the backoff doubled). Failures
        against an already-replaced Server are dropped — they must not
        trip the fresh replica's breaker."""
        with self._lock:
            if rep.server is not srv:
                return
            if probe:
                rep.probing = False
            rep.failures += 1
            opened = False
            if (rep.breaker != BREAKER_OPEN
                    and (probe
                         or rep.failures >= self.breaker_threshold)):
                if rep.breaker == BREAKER_HALF_OPEN:
                    rep.backoff_mult *= 2.0
                rep.breaker = BREAKER_OPEN
                rep.opens += 1
                backoff = min(
                    self.breaker_backoff_s * rep.backoff_mult,
                    self.breaker_backoff_max_s)
                rep.open_until = time.monotonic() + backoff
                opened = True
        if opened:
            self._breaker_metric(rep)
            if trace.enabled():
                trace.event("breaker", replica=rep.index, state="open",
                            failures=rep.failures, cause=repr(err),
                            router=self.monitor_router)

    def _clear_probe(self, rep: _Replica, srv, probe: bool) -> None:
        """Release a half-open probe slot on a verdict that is neither
        replica-success nor replica-failure (user cancel, deadline
        expiry, request-scoped terminal): the breaker stays HALF-OPEN
        and the NEXT request becomes the new probe — without this the
        abandoned probe would block every future pick forever. Same
        server-identity guard as the other transition helpers: a
        STALE probe's late verdict must not clear the slot a fresh
        server's probe currently holds (two concurrent probes would
        double the load on a recovering replica)."""
        if not probe:
            return
        with self._lock:
            if rep.server is srv:
                rep.probing = False

    def _replica_success(self, rep: _Replica, srv,
                         probe: bool) -> None:
        """A request made real progress on the replica (first token or
        completion): reset the consecutive-failure count and CLOSE a
        half-open breaker (the probe succeeded)."""
        with self._lock:
            if rep.server is not srv:
                return
            if probe:
                rep.probing = False
            rep.failures = 0
            closed = rep.breaker != BREAKER_CLOSED
            rep.breaker = BREAKER_CLOSED
            rep.backoff_mult = 1.0
        if closed:
            self._breaker_metric(rep)
            if trace.enabled():
                trace.event("breaker", replica=rep.index,
                            state="closed",
                            router=self.monitor_router)

    # -- routing -------------------------------------------------------------
    def _acquire(self, exclude, hard=frozenset(), adapter=None,
                 headroom_s=None):
        """Pick the least-loaded routable replica: status ``ok``
        (warming/degraded/failed/draining/restarting/dead excluded),
        breaker not OPEN (an elapsed OPEN transitions to HALF-OPEN
        here and admits this caller as its ONE probe). ``exclude``
        skips the replica a failure just came from — unless it is the
        only candidate; ``hard`` (replicas this request can NEVER fit
        — heterogeneous fleets) is skipped unconditionally.
        ``adapter`` biases the pick with ADAPTER AFFINITY: replicas
        with the named LoRA adapter RESIDENT score ahead of those
        without (an atomic registry-membership read — no HTTP, no
        device sync), falling back to plain least-loaded when nobody
        has it; the load tie-break still applies within each class,
        so affinity never pins a tenant to one overloaded replica
        while an idle adapter-resident peer exists. ``headroom_s``
        (remaining SLO deadline) below ``tight_headroom_s`` drops the
        affinity term entirely — the deadline-tight pick is purely
        least-loaded (ROADMAP 2c: deadline headroom outranks warmth).
        Returns ``(rep, server, probe)`` or ``(None, None, False)``."""
        if (headroom_s is not None
                and headroom_s < self.tight_headroom_s):
            adapter = None
        now = time.monotonic()
        flipped = []
        with self._lock:
            cands = []
            for rep in self._replicas:
                if rep.index in hard:
                    continue
                if rep.dead or rep.draining or rep.restart_at is not None:
                    continue
                if rep.breaker == BREAKER_OPEN:
                    if now < rep.open_until:
                        continue
                    rep.breaker = BREAKER_HALF_OPEN
                    flipped.append(rep)
                    half = True
                else:
                    half = rep.breaker == BREAKER_HALF_OPEN
                if half and rep.probing:
                    continue   # one probe at a time
                cands.append((rep, half))
            picks = [(r, hf) for r, hf in cands
                     if r.index not in exclude] or cands
            # replicas inside a Retry-After honor window lose to any
            # sibling outside one — same only-candidate fallback as
            # ``exclude`` so the hint never starves a request
            picks = [(r, hf) for r, hf in picks
                     if self._reject_until.get(r.index, 0.0) <= now
                     ] or picks
            best = None
            best_score = None
            best_half = False
            for rep, half in picks:
                # the same host-side fields Server.load() reports,
                # read directly: this runs per candidate per pick
                # (and on every waiting pump's retry tick) under the
                # router lock — materializing the whole /healthz
                # payload here would serialize healthy routing behind
                # the spin
                srv2 = rep.server
                try:
                    if srv2.status != "ok":
                        continue
                    alloc = getattr(srv2.engine, "alloc", None)
                    # adapter affinity first (0 = resident, 1 = not:
                    # an admission on a resident replica reuses its
                    # bank row AND its adapter-salted prefix cache),
                    # then least-loaded: what's queued + what's
                    # decoding now; free pages break ties toward the
                    # roomier KV pool
                    # skew first, THEN adapter affinity, then load: a
                    # slow replica with the adapter resident loses to a
                    # healthy one without it — a warm bank row saves
                    # milliseconds, a skewed replica costs the whole
                    # TPOT gap, and the SLO is the thing being served.
                    # Slow stays a candidate (routable of last resort;
                    # slow != open breaker).
                    reg = getattr(srv2.engine, "adapters", None)
                    afar = int(not (adapter is not None
                                    and reg is not None
                                    and adapter in reg))
                    score = (int(rep.slow),
                             afar if adapter is not None else 0,
                             srv2.queue.depth + srv2.num_active(),
                             -(alloc.free_pages if alloc is not None
                               else 0))
                except Exception:
                    continue
                if best_score is None or score < best_score:
                    best, best_score, best_half = rep, score, half
            if best is not None and best_half:
                best.probing = True
            srv = best.server if best is not None else None
        for rep in flipped:   # gauge reflects the OPEN -> HALF_OPEN
            #                   flip even for candidates not picked
            self._breaker_metric(rep)
            if trace.enabled():
                trace.event("breaker", replica=rep.index,
                            state="half_open",
                            router=self.monitor_router)
        if best is None:
            return None, None, False
        return best, srv, best_half

    def _all_dead(self) -> bool:
        with self._lock:
            return all(rep.dead for rep in self._replicas)

    def _live_indices(self) -> set:
        with self._lock:
            return {rep.index for rep in self._replicas
                    if not rep.dead}

    # -- the per-request pump ------------------------------------------------
    def _run_request(self, h: RouterHandle) -> None:
        try:
            self._pump(h)
        except BaseException as e:   # noqa: BLE001 - client must not hang
            h._finish(FAILED, e)
            self._count("failed", h.replica)
        finally:
            with self._lock:
                self._handles.discard(h)
            with self._idle_cv:
                self._idle_cv.notify_all()

    def _pump(self, h: RouterHandle) -> None:
        """Own one request end to end: pick a replica, submit
        ``prompt + tokens streamed so far`` with the remaining budget,
        relay the inner stream into the router handle, and on a
        replica-attributed failure park nothing — fail over
        immediately (bounded by ``max_failovers``). Greedy failover is
        bitwise-identical to an unfaulted run: the resubmit is a
        causal re-prefill of the exact emitted prefix, the same
        argument (and test bar) as the in-engine replay."""
        last_err = None
        exclude: set = set()
        nofit: set = set()   # replicas whose CAPACITY verdict said
        #                      this request can never fit there
        #                      (heterogeneous fleets: per-spec, not
        #                      per-fleet)
        while True:
            with self._lock:
                stopping = self._stopping
            if stopping or h._cancel_requested:
                h._finish(CANCELLED)
                self._count("cancelled", h.replica)
                return
            if (h.deadline is not None and not h._ever_admitted
                    and time.monotonic() >= h.deadline):
                h._finish(EXPIRED)
                self._count("expired", h.replica)
                return
            done = h.tokens_so_far()
            remaining = h.cfg.max_new_tokens - len(done)
            if remaining < 1:   # budget fully streamed before the
                #                 failover landed: simply finished
                h._finish(FINISHED)
                self._count("completed", h.replica)
                return
            rep, srv, probe = self._acquire(
                exclude, hard=frozenset(nofit),
                adapter=getattr(h.cfg, "adapter", None),
                headroom_s=(None if h.deadline is None
                            else h.deadline - time.monotonic()))
            if rep is None:
                if self._all_dead():
                    h._finish(FAILED, FleetUnavailable(
                        f"request {h.id}: every replica is permanently "
                        f"dead (last error: {last_err!r})"))
                    self._count("failed", h.replica)
                    return
                if nofit and self._live_indices() <= nofit:
                    # every replica that could ever come back has
                    # already given a capacity verdict: terminal
                    h._finish(FAILED, last_err or RequestFailed(
                        f"request {h.id} fits no replica"))
                    self._count("failed", h.replica)
                    return
                # transient: all replicas warming / restarting /
                # breaker-open — wait, bounded by the deadline check
                # at the top of the loop
                time.sleep(self.retry_wait_s)
                exclude = set()
                continue
            ids = (np.concatenate(
                [_prompt_ids(h.prompt)[0],
                 np.asarray(done, np.int32)])
                if done else h.prompt)
            kw = dict(vars(h.cfg))
            kw["max_new_tokens"] = remaining
            rcfg = GenerationConfig(**kw)
            # admission deadline: only until the FIRST successful
            # admission (PR 4/5 replay semantics — met once is met)
            t_s = None
            if h.deadline is not None and not h._ever_admitted:
                t_s = max(h.deadline - time.monotonic(), 1e-3)
            try:
                inner = srv.submit(ids, rcfg, priority=h.priority,
                                   timeout_s=t_s,
                                   trace_rid=h._trace_rid,
                                   tenant=h.tenant)
            except RequestRejected as e:
                # replica-attributed only when the REPLICA is the
                # problem; queue_full is load, not sickness — routing
                # just looks elsewhere
                if e.reason in ("degraded", "shutdown"):
                    self._replica_failure(rep, srv, e, probe)
                else:
                    self._clear_probe(rep, srv, probe)
                last_err = e
                exclude = {rep.index}
                # NOT counted on the router requests counter: every
                # other outcome there is per-request-terminal, and a
                # waiting pump retries ~50x/s — the replica's own
                # serving_requests_total{event=rejected_*} already
                # counts backpressure per attempt
                # honor the replica's Retry-After before re-routing to
                # IT: the reject window keeps _acquire off this
                # replica until the hint elapses (bounded), while the
                # pump itself stays on its fast tick so a healthy
                # sibling picks the request up immediately
                if getattr(e, "retry_after_s", None) is not None:
                    with self._lock:
                        self._reject_until[rep.index] = (
                            time.monotonic()
                            + min(max(float(e.retry_after_s), 0.0),
                                  2.0))
                # a rejection (queue_full on every replica, say) must
                # not busy-spin the pump: one retry tick of backoff
                time.sleep(self.retry_wait_s)
                continue
            except ValueError as e:   # capacity verdict: this request
                #                       can never fit THIS replica
                self._clear_probe(rep, srv, probe)
                if self._homogeneous:
                    # same spec everywhere: the verdict is fleet-wide
                    h._finish(FAILED, e)
                    self._count("failed", rep.index)
                    return
                nofit.add(rep.index)
                last_err = e
                continue   # a larger-spec replica may still hold it;
                #            the no-replica branch above terminals
                #            once every live replica has said no
            except Exception as e:    # server died mid-submit
                self._replica_failure(rep, srv, e, probe)
                last_err = e
                exclude = {rep.index}
                continue
            h._inner = inner
            h.replica = rep.index
            if h._cancel_requested:
                inner.cancel()
            if trace.enabled():
                trace.event("route", rid=h._trace_rid,
                            replica=rep.index,
                            failovers=h._failovers,
                            resubmit=bool(done),
                            router=self.monitor_router)
            verdict, err = self._relay(h, rep, srv, inner, probe)
            if verdict == "finished":
                h._finish(FINISHED)
                self._count("completed", rep.index)
                return
            if verdict == "cancelled":
                self._clear_probe(rep, srv, probe)
                h._finish(CANCELLED)
                self._count("cancelled", rep.index)
                return
            if verdict == "expired":
                self._clear_probe(rep, srv, probe)
                h._finish(EXPIRED)
                self._count("expired", rep.index)
                return
            if verdict == "terminal":
                self._clear_probe(rep, srv, probe)
                if not self._homogeneous:
                    # per-replica capacity verdict (PagePoolExhausted
                    # is pool-size-dependent): a roomier spec may
                    # still serve the request
                    nofit.add(rep.index)
                    last_err = err
                    continue
                h._finish(FAILED, err)
                self._count("failed", rep.index)
                return
            # verdict == "failover" (the replica died/degraded under a
            # live request — breaker-accountable) or "overload" (a
            # pressure verdict: migrate, but the replica stays in good
            # standing). Both consume the failover budget: a request
            # bouncing between pressured pools must still terminate,
            # and FailoverBudgetExceeded chains the pressure cause.
            if verdict == "overload":
                self._clear_probe(rep, srv, probe)
            else:
                self._replica_failure(rep, srv, err, probe)
            with self._lock:
                stopping = self._stopping
            if stopping or h._cancel_requested:
                continue   # loop head finishes it CANCELLED (a fleet
                #            shutdown is not a failover)
            h._failovers += 1
            with self._lock:
                self._failovers_total += 1
            self._count("failover", rep.index)
            if monitor.enabled():
                self._failovers_counter().labels(
                    router=self.monitor_router).inc()
            if trace.enabled():
                trace.event("failover", rid=h._trace_rid,
                            replica=rep.index, n=h._failovers,
                            emitted=len(h.tokens_so_far()),
                            cause=repr(err),
                            router=self.monitor_router)
            if h._failovers > self.max_failovers:
                h._finish(FAILED, FailoverBudgetExceeded(
                    f"request {h.id} failed over {h._failovers} times "
                    f"(max_failovers={self.max_failovers}); last "
                    f"replica fault: {err!r}"))
                self._count("failed", rep.index)
                return
            last_err = err
            exclude = {rep.index}

    @staticmethod
    def _wait_progress(inner, sent: int, timeout: float):
        """Wait (bounded) for the inner handle to grow past ``sent``
        tokens or reach a terminal state; returns
        ``(delta, status, err)`` read atomically under the handle's
        condition — at a terminal state the delta IS everything that
        remains, so a failover's resubmit prefix is never torn."""
        with inner._cv:
            inner._cv.wait_for(
                lambda: (len(inner._tokens) > sent
                         or inner._status in _TERMINAL), timeout)
            return (list(inner._tokens[sent:]), inner._status,
                    inner._error)

    def _relay(self, h: RouterHandle, rep: _Replica, srv, inner,
               probe: bool):
        """Relay one inner handle's tokens into the router handle.
        Returns ``(verdict, err)`` with verdict one of ``finished`` /
        ``cancelled`` (user cancel) / ``expired`` / ``terminal``
        (request-scoped failure any replica would repeat) /
        ``failover`` (replica-attributed — resubmit elsewhere)."""
        sent = 0
        got_any = False
        while True:
            delta, status, err = self._wait_progress(
                inner, sent, self.degraded_poll_s)
            if (inner.engine_rid is not None
                    and h.engine_rid != inner.engine_rid):
                # the replica COMPLETED this request's admission: the
                # admission deadline is met (PR 4/5 replay semantics —
                # met once is met), so a later failover must REPLAY,
                # never expire, it — even if the replica dies between
                # admission and the first token reaching the pump.
                # The ROUTER handle goes RUNNING here too, tracking
                # the CURRENT engine rid (same client surface as
                # RequestHandle: status must not read "queued" while
                # tokens stream)
                h._ever_admitted = True
                h._mark_running(inner.engine_rid)
            if delta:
                sent += len(delta)
                h._push(delta)
                h._n_pushed += len(delta)
                if not got_any:
                    got_any = True
                    # first token = the replica admitted AND decoded:
                    # the half-open probe's success signal (don't hold
                    # the breaker hostage to a long generation)
                    self._replica_success(rep, srv, probe)
            if status == FINISHED:
                self._replica_success(rep, srv, probe)
                return "finished", None
            if status == CANCELLED:
                # either the user asked, or the replica cancelled it
                # on its way down (shutdown finalizer) — the latter is
                # a failover
                if h._cancel_requested:
                    return "cancelled", None
                return "failover", RuntimeError(
                    f"replica {rep.index} cancelled the request on "
                    "its way down")
            if status == EXPIRED:
                return "expired", None
            if status == FAILED:
                if isinstance(err, (ValueError, PagePoolExhausted)):
                    # request-scoped capacity verdict: identical
                    # replicas would all repeat it — fail the request,
                    # spare the fleet
                    return "terminal", err
                if isinstance(err, PreemptionBudgetExceeded):
                    # a LOAD verdict, not sickness (the replica is
                    # healthy, its pool is just thrashing): migrate
                    # the request — another replica may have room —
                    # but do NOT blame the breaker, or a pressured
                    # fleet walls off its own healthy replicas and
                    # cascades the load onto equally pressured peers
                    return "overload", err
                return "failover", (err if err is not None
                                    else RequestFailed(
                                        f"replica {rep.index} failed "
                                        "the request"))
            if not delta:
                # a silent poll tick: re-check the replica's health
                # instead of waiting on a corpse — this is how a
                # DEGRADED (stalled) replica loses its live requests
                # before its own watchdog even recovers
                st = srv.status
                if st in ("degraded", "failed", "stopped"):
                    inner.cancel()   # if it un-wedges, reclaim there
                    return "failover", RuntimeError(
                        f"replica {rep.index} {st} mid-stream")
                if h._cancel_requested:
                    inner.cancel()

    # -- replica supervision (monitor thread) --------------------------------
    def _monitor_loop(self) -> None:
        """Restart crashed/failed replicas from their spec with
        exponential backoff. Detection: ``Server.status`` in
        ``failed``/``stopped`` outside a deliberate drain/restart.
        Budget: ``max_replica_restarts`` per replica; past it the
        replica is DEAD (breaker pinned open, fleet serves on).
        The SKEW DETECTOR rides the same thread on its own (coarser)
        cadence — reading N rolling digests is host work, but not
        every-50ms work."""
        last_skew = 0.0
        last_elastic = 0.0
        while not self._stop_evt.wait(self.monitor_interval_s):
            for rep in list(self._replicas):
                self._supervise(rep)
            now = time.monotonic()
            if now - last_skew >= self.skew_interval_s:
                last_skew = now
                try:
                    self._check_skew()
                except Exception:
                    # skew is ADVISORY: a torn read off a mid-rebuild
                    # replica (or a dump-path surprise) must never
                    # kill the supervision thread that restarts
                    # crashed replicas
                    pass
            if (self._elastic is not None
                    and now - last_elastic >= self.elastic_interval_s):
                last_elastic = now
                try:
                    self._elastic_tick(now)
                except Exception:
                    # same bar as skew: sizing is advisory, crash
                    # supervision must keep running
                    pass

    def _check_skew(self) -> None:
        """Slow-replica skew detection (monitor thread): compare each
        live replica's rolling-window TPOT p50 (the SLO tracker's
        :meth:`~paddle_tpu.monitor.slo.SLOTracker.rolling_tpot_p50`)
        against the fleet median of the OTHER judged replicas' p50s —
        leave-one-out, so a lagging replica cannot drag its own
        baseline up, and a 2-replica fleet stays detectable (a global
        median over two is the mean of both, which ``p > factor ×
        median`` could never exceed at ``factor >= 2``). A replica
        above ``skew_factor``× its peers' median flips SLOW. This is the failure
        mode the circuit breakers are blind to: a replica that is
        *slow but alive* (thermal throttling, a neighbour hogging the
        host, a wedged-but-recovering pool) answers every request and
        never trips a failure counter — but it drags the fleet p99.
        SLOW is a ROUTING HINT, not a wall: the replica scores behind
        every non-slow candidate in ``_acquire`` yet stays routable
        (slow ≠ open breaker), surfaces in ``load()``/``GET /stats``,
        and the flip dumps the flight recorder (one dump per flip —
        the black box alongside PR 8's storm/stall triggers).

        A replica needs ``skew_min_requests`` TPOT observations inside
        the rolling window to be judged (a starved or freshly
        restarted replica reads UNKNOWN → not slow), and a verdict
        needs >= 1 OTHER judged replica — a fleet of one has nothing
        to skew against."""
        with self._lock:
            reps = list(self._replicas)
        p50s = {}
        for rep in reps:
            if rep.dead or rep.restart_at is not None:
                continue
            tracker = getattr(rep.server, "slo", None)
            if tracker is None:
                continue
            try:
                p = tracker.rolling_tpot_p50(
                    min_count=self.skew_min_requests)
            except Exception:   # mid-swap replica: skip this round
                p = None
            if p is not None:
                p50s[rep.index] = p
        for rep in reps:
            p = p50s.get(rep.index)
            others = [v for i, v in p50s.items() if i != rep.index]
            med = statistics.median(others) if others else None
            slow = (med is not None and med > 0 and p is not None
                    and p > self.skew_factor * med)
            with self._lock:
                changed = (not rep.dead and rep.slow != slow)
                if changed:
                    rep.slow = slow
            if not changed:
                continue
            self._slow_metric(rep)
            if trace.enabled():
                trace.event("replica.slow", replica=rep.index,
                            slow=slow,
                            tpot_p50_s=(None if p is None
                                        else round(p, 6)),
                            fleet_median_s=(None if med is None
                                            else round(med, 6)),
                            factor=self.skew_factor,
                            router=self.monitor_router)
            if slow:
                self._flight_dump(f"replica_slow_{rep.index}")

    # -- elastic fleet sizing (monitor thread / scale_to) --------------------
    def _elastic_signals(self):
        """Host-side autoscaler inputs: the currently-serving replica
        records, their summed queue depth + active work, and the
        hottest tenant fast-burn rate across their SLO trackers (0.0
        while the monitor is off or no window has data). All
        lock-light reads — same discipline as routing."""
        with self._lock:
            serving = [rep for rep in self._replicas
                       if not (rep.dead or rep.draining
                               or rep.scaled_down
                               or rep.restart_at is not None)]
        depth = 0
        burn = 0.0
        for rep in serving:
            try:
                depth += rep.server.queue.depth + rep.server.num_active()
            except Exception:   # mid-swap replica: skip its numbers
                continue
            if monitor.enabled():
                tracker = getattr(rep.server, "slo", None)
                if tracker is not None:
                    try:
                        burn = max(burn,
                                   max_burn(tracker.tenant_stats()))
                    except Exception:
                        pass
        return serving, depth, burn

    def _elastic_tick(self, now: float) -> None:
        """One autoscaler pass (supervisor thread): feed occupancy +
        queue depth + burn into the :class:`ElasticController` —
        which owns the hysteresis (consecutive-signal streaks) and
        the rate limit (cooldown) — and apply at most ONE replica of
        change. Scale-down drains (never kills in-flight work);
        scale-up revives a parked slot from its own spec."""
        serving, depth, burn = self._elastic_signals()
        d = self._elastic.decide(now, routable=len(serving),
                                 queue_depth=depth, burn_max=burn)
        if d > 0:
            self._scale_up(depth=depth, burn=burn)
        elif d < 0:
            self._scale_down(depth=depth, burn=burn)

    def _scale_down(self, depth: int = 0, burn: float = 0.0):
        """Park the least-loaded serving replica: excluded from
        routing immediately (draining), then drained WITHOUT a
        timeout on a helper thread — every queued + in-flight request
        runs to completion (the PR 9 rolling-restart bar: elastic
        scale-down never fails a handle) — and only then shut down.
        The slot stays in the fleet as ``scaled_down`` so a later
        scale-up revives it from ITS spec (device pinning included).
        Returns the drain thread, or None if no replica can be
        spared."""
        with self._lock:
            cands = [rep for rep in self._replicas
                     if not (rep.dead or rep.draining
                             or rep.scaled_down
                             or rep.restart_at is not None)]
            if len(cands) < 2:   # never park the last serving replica
                return None

            def _load(rep):
                try:
                    return (rep.server.queue.depth
                            + rep.server.num_active())
                except Exception:
                    return 0

            # least-loaded victim (fewest requests to wait out), ties
            # to the highest index — deterministic under equal load
            victim = min(cands, key=lambda r: (_load(r), -r.index))
            victim.draining = True
            victim.scaled_down = True
            srv = victim.server
        if trace.enabled():
            trace.event("control.scale", action="down",
                        replica=victim.index,
                        queue_depth=depth, burn=round(burn, 3),
                        router=self.monitor_router)
        if monitor.enabled():
            self._scale_counter().labels(
                router=self.monitor_router, action="down").inc()
        self._replicas_metric()
        t = threading.Thread(
            target=self._finish_scale_down, args=(victim, srv),
            daemon=True,
            name=f"paddle_tpu-router-scaledown-{self.monitor_router}"
                 f"-{victim.index}")
        t.start()
        return t

    def _finish_scale_down(self, rep: _Replica, srv) -> None:
        """Drain-then-stop half of a scale-down (helper thread): the
        unbounded drain is the point — in-flight work finishes no
        matter how long it decodes; only an empty server stops."""
        try:
            srv.drain(None)
        except Exception:
            pass
        try:
            srv.shutdown(drain=False, timeout=5.0)
        except Exception:
            pass
        try:
            eng = getattr(srv, "engine", None)
            if eng is not None:
                eng.close()
        except Exception:
            pass

    def _scale_up(self, depth: int = 0, burn: float = 0.0,
                  timeout: Optional[float] = None) -> bool:
        """Revive the lowest-index parked (scaled-down) slot: rebuild
        from its spec OUTSIDE the lock (same as supervised restarts —
        routing never blocks on a build), wait for warmup, swap it in
        with a clean breaker. Returns True when a slot was revived
        (False: nothing parked, or a racing shutdown/restart won)."""
        with self._lock:
            parked = [rep for rep in self._replicas
                      if rep.scaled_down and not rep.dead]
            if not parked or self._stopping:
                return False
            rep = min(parked, key=lambda r: r.index)
            old = rep.server
        new = rep.spec.build()
        new.wait_ready(timeout)
        with self._lock:
            if (self._stopping or rep.dead or not rep.scaled_down
                    or rep.server is not old):
                stale = new   # a shutdown/deliberate-restart won the
                #               race mid-build: its server stays
            else:
                stale = None
                rep.reset_health(server=new)
                rep.draining = False
                rep.scaled_down = False
        if stale is not None:
            try:
                stale.shutdown(drain=False, timeout=5.0)
            except Exception:
                pass
            return False
        self._breaker_metric(rep)
        self._slow_metric(rep)
        self._replicas_metric()
        if trace.enabled():
            trace.event("control.scale", action="up",
                        replica=rep.index,
                        queue_depth=depth, burn=round(burn, 3),
                        router=self.monitor_router)
        if monitor.enabled():
            self._scale_counter().labels(
                router=self.monitor_router, action="up").inc()
        return True

    def scale_to(self, n: int, timeout: Optional[float] = None) -> int:
        """Deliberately size the fleet to ``n`` serving replicas
        (clamped to ``[1, len(specs)]``), bypassing the autoscaler's
        hysteresis — the operator knob (and the deterministic test
        surface). Scale-downs drain on helper threads; with
        ``timeout`` the call waits (bounded) for those drains. Returns
        the serving-replica count after the call."""
        n = max(1, min(n, len(self._replicas)))
        threads = []
        while True:
            with self._lock:
                serving = sum(1 for r in self._replicas
                              if not (r.dead or r.draining
                                      or r.scaled_down
                                      or r.restart_at is not None))
            if serving > n:
                t = self._scale_down()
                if t is None:
                    break
                threads.append(t)
            elif serving < n:
                if not self._scale_up(timeout=timeout):
                    break
            else:
                break
        for t in threads:
            t.join(timeout)
        with self._lock:
            return sum(1 for r in self._replicas
                       if not (r.dead or r.draining or r.scaled_down
                               or r.restart_at is not None))

    def _supervise(self, rep: _Replica) -> None:
        now = time.monotonic()
        with self._lock:
            if (self._stopping or rep.dead or rep.draining):
                return
            srv = rep.server
            pending = rep.restart_at
        if pending is None:
            if srv.status not in ("failed", "stopped"):
                return
            with self._lock:
                if rep.server is not srv or rep.draining:
                    return
                if rep.restarts >= self.max_replica_restarts:
                    rep.mark_dead()
                    self._breaker_metric(rep)
                    self._slow_metric(rep)   # mark_dead cleared slow
                    if trace.enabled():
                        trace.event(
                            "replica.dead", replica=rep.index,
                            restarts=rep.restarts,
                            router=self.monitor_router)
                    return
                rep.restarts += 1
                delay = self._backoff_delay(rep.restarts)
                rep.restart_at = now + delay
            if trace.enabled():
                trace.event("replica.backoff", replica=rep.index,
                            restarts=rep.restarts,
                            delay_s=round(delay, 4),
                            router=self.monitor_router)
            return
        if now < pending:
            return
        # backoff elapsed: rebuild OUTSIDE the lock (engine/device
        # construction takes real time; routing must not block on it)
        try:
            try:
                srv.shutdown(drain=False, timeout=2.0)
            except Exception:
                pass
            new = rep.spec.build()
        except Exception as e:
            with self._lock:
                if (rep.server is not srv or rep.draining
                        or self._stopping):
                    return   # the slot changed hands mid-build (a
                    #          deliberate restart/shutdown): not ours
                    #          to mark dead or re-schedule
                if rep.restarts >= self.max_replica_restarts:
                    rep.mark_dead()
                else:
                    rep.restarts += 1
                    rep.restart_at = (time.monotonic()
                                      + self._backoff_delay(
                                          rep.restarts))
            self._breaker_metric(rep)
            self._slow_metric(rep)   # the mark_dead branch cleared slow
            if trace.enabled():
                trace.event("replica.rebuild_failed",
                            replica=rep.index, cause=repr(e),
                            router=self.monitor_router)
            return
        with self._lock:
            if rep.server is not srv or rep.draining or self._stopping:
                stale = new   # a deliberate restart_replica (or a
                #               shutdown) won the race while we built:
                #               ITS server stays — ours must not
                #               silently replace and leak it
            else:
                stale = None
                rep.reset_health(server=new)
        if stale is not None:
            try:
                stale.shutdown(drain=False, timeout=5.0)
            except Exception:
                pass
            return
        self._breaker_metric(rep)
        self._slow_metric(rep)   # reset_health cleared slow out of band
        if monitor.enabled():
            self._restarts_counter().labels(
                router=self.monitor_router,
                replica=str(rep.index)).inc()
        if trace.enabled():
            trace.event("replica.restart", replica=rep.index,
                        restarts=rep.restarts, deliberate=False,
                        router=self.monitor_router)

    def _backoff_delay(self, restarts: int) -> float:
        """Exponential supervised-restart backoff before attempt
        ``restarts`` (1-based), capped at
        ``replica_backoff_max_s``."""
        return min(self.replica_backoff_s * (2 ** (restarts - 1)),
                   self.replica_backoff_max_s)
