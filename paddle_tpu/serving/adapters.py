"""Multi-tenant LoRA adapter registry + device bank (S-LoRA-style).

ONE engine serves hundreds of fine-tunes: the PR 2 invariant (per-slot
*device vectors* so one compiled program serves any request mix)
generalizes from sampling params to WEIGHTS. All resident adapters'
low-rank factors are stacked into fixed-shape device arrays per target
projection — ``A: [L, K+1, r, d_in]`` / ``B: [L, K+1, d_out, r]`` per
target (L = model layers, K = :attr:`AdapterRegistry.capacity`, r =
the bank rank) — and every decode/prefill program gathers each slot's
factors by its ``adapter_idx`` device vector INSIDE the compiled
program. Index 0 is the base model: its rows are zeros, so the gathered
delta is exactly 0.0 and base rows stay bitwise-identical to a
LoRA-free engine. Loading/unloading an adapter only rewrites bank ROWS
(fixed shapes), so the serving programs never recompile per adapter.

The registry is the host-side half: name -> bank index, per-index
refcounts (live slots currently decoding under the adapter), hot
``load``/``unload`` with UNLOAD DEFERRAL (an unload while any live slot
references the index marks it draining; the index frees — and becomes
recyclable — when the last reference releases), and a per-load
GENERATION salt for the prefix cache (chain hashes are salted with
``name@generation``, so KV cached under one adapter can never alias
another adapter's — or a later reload's — admission).

Thread model: like :class:`~paddle_tpu.inference.paged_cache.PageAllocator`,
all mutating calls run on the engine-driving (scheduler) thread between
decode segments — ``Server.load_adapter``/``unload_adapter`` marshal
admin requests into the inter-segment gap. Cross-thread readers
(``/healthz`` via ``engine.load()``, the router's adapter-affinity
probe) take atomic dict/int snapshots only.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from .. import monitor
from .. import tracing as trace

__all__ = ["AdapterRegistry"]


class AdapterRegistry:
    """Registry + device bank for up to ``capacity`` resident LoRA
    adapters (bank index 0 = base model, rows pinned to zeros).

    ``shapes`` maps each target projection name to its ``(d_in, d_out)``
    (the model's ``lora_shapes`` hook provides it); ``num_layers`` is
    the depth of the per-layer factor stacks. ``rank`` is the BANK rank:
    adapters with a smaller rank zero-pad up to it (padded rows
    contribute exactly 0 to the delta), larger ranks are rejected —
    the bank shapes are the compiled programs' shapes.
    """

    def __init__(self, capacity: int, rank: int, targets, num_layers: int,
                 shapes: Dict[str, Tuple[int, int]], dtype,
                 engine_label: str):
        if not isinstance(capacity, int) or isinstance(capacity, bool) \
                or capacity < 1:
            raise ValueError(
                f"lora capacity must be an int >= 1, got {capacity!r}")
        if not isinstance(rank, int) or isinstance(rank, bool) \
                or rank < 1:
            raise ValueError(
                f"lora rank must be an int >= 1, got {rank!r}")
        targets = tuple(targets)
        if not targets:
            raise ValueError("lora needs at least one target projection")
        missing = [t for t in targets if t not in shapes]
        if missing:
            raise ValueError(
                f"model provides no lora shapes for target(s) {missing}")
        self.capacity = int(capacity)
        self.rank = int(rank)
        self.targets = targets
        self.num_layers = int(num_layers)
        self.shapes = {t: shapes[t] for t in targets}
        self.dtype = dtype
        self._engine = engine_label
        # device bank: fixed shapes for the whole registry lifetime —
        # the serving programs close over NOTHING here; the engine
        # passes these arrays as jit arguments, so a load/unload only
        # changes DATA (zero recompiles per adapter)
        K = self.capacity
        L = self.num_layers
        self.bank = {
            t: (jnp.zeros((L, K + 1, self.rank, d_in), dtype),
                jnp.zeros((L, K + 1, d_out, self.rank), dtype))
            for t, (d_in, d_out) in self.shapes.items()}
        # guarded-by: scheduler-thread (mutations run between segments;
        # cross-thread readers take atomic snapshots — __contains__,
        # resident())
        self._names: Dict[str, int] = {}       # name -> bank index
        self._name_of: Dict[int, str] = {}     # index -> name
        self._salt: Dict[int, bytes] = {}      # index -> prefix salt
        self._refs: Dict[int, int] = {}        # index -> live slots
        self._draining: set = set()            # unload deferred
        self._free: List[int] = list(range(1, K + 1))
        self._gen = 0                          # per-load generation:
        #                                        salts a reload of the
        #                                        same name differently
        # ONE jitted row-install shared by every target (jit re-keys on
        # the per-target shapes); compile time lands on the monitored
        # counters and engine.warmup() pre-pays it per target

        def install(A, B, a, b, i):
            return A.at[:, i].set(a), B.at[:, i].set(b)

        self._install = monitor.monitored_jit(install,
                                              name="lora_install",
                                              owner=self._engine,
                                              donate_argnums=(0, 1))

    # -- lifecycle (engine-driving thread, between segments) -----------------
    def load(self, name: str, params: Dict, alpha=None) -> int:
        """Install one adapter into a free bank index; returns it.

        ``params`` maps target names (a subset of the registry's
        ``targets``) to ``(A, B)`` factor pairs: ``A`` is ``[r_a, d_in]``
        (shared across layers) or ``[L, r_a, d_in]`` (per layer), ``B``
        likewise ``[d_out, r_a]`` / ``[L, d_out, r_a]``, with
        ``r_a <= rank`` (zero-padded up). The LoRA scaling
        ``alpha / r_a`` (``alpha`` defaults to ``r_a`` — scale 1.0) is
        folded into ``B`` at install, so serving pays no extra multiply.
        Raises ValueError for an unknown/duplicate name, a full
        registry, or malformed factors; the bank is untouched on any
        failure."""
        if not isinstance(name, str) or not name or len(name) > 256:
            # the same bound GenerationConfig.adapter enforces — a name
            # loadable here but unreachable by any request would occupy
            # a bank index forever
            raise ValueError(f"adapter name must be a non-empty str "
                             f"(<= 256 chars), got {name!r}")
        if name in self._names:
            state = ("still unloading (live requests reference it)"
                     if self._names[name] in self._draining
                     else "already loaded")
            raise ValueError(f"adapter {name!r} {state}; unload first")
        if not self._free:
            raise ValueError(
                f"adapter registry full ({self.capacity} resident); "
                f"unload one first")
        if not isinstance(params, dict) or not params:
            raise ValueError(
                "adapter params must be a non-empty dict "
                "{target: (A, B)}")
        unknown = sorted(set(params) - set(self.targets))
        if unknown:
            raise ValueError(
                f"adapter {name!r} targets {unknown} not in the "
                f"engine's lora_targets {self.targets}")
        # validate + normalize EVERYTHING before touching the bank: a
        # half-installed adapter must be impossible
        staged = {}
        for t, ab in params.items():
            staged[t] = self._stage_target(name, t, ab, alpha)
        idx = self._free.pop(0)
        for t, (a, b) in staged.items():
            A, B = self.bank[t]
            self.bank[t] = self._install(A, B, a, b, jnp.int32(idx))
        untouched = [t for t in self.targets if t not in staged]
        if untouched:
            # a recycled index may hold a PREVIOUS adapter's rows for
            # targets this one does not provide — zero them, or the new
            # adapter would silently inherit stale deltas
            for t in untouched:
                A, B = self.bank[t]
                L = self.num_layers
                d_in, d_out = self.shapes[t]
                self.bank[t] = self._install(
                    A, B, jnp.zeros((L, self.rank, d_in), self.dtype),
                    jnp.zeros((L, d_out, self.rank), self.dtype),
                    jnp.int32(idx))
        self._gen += 1
        self._names[name] = idx
        self._name_of[idx] = name
        # generation-salted: a later reload of the same NAME gets a new
        # salt, so prefix-cache pages parked under the old weights can
        # never warm-hit the new ones
        self._salt[idx] = f"{name}@{self._gen}".encode()
        self._refs[idx] = 0
        if monitor.enabled():
            self._resident_gauge().labels(engine=self._engine).set(
                len(self._names))
        if trace.enabled():
            trace.event("lora.load", adapter=name, index=idx,
                        engine=self._engine)
        return idx

    def _stage_target(self, name: str, t: str, ab, alpha):
        """Validate one target's (A, B) pair and return the padded,
        scale-folded, per-layer device arrays."""
        try:
            a_raw, b_raw = ab
        except Exception:
            raise ValueError(
                f"adapter {name!r} target {t!r} must be an (A, B) "
                f"pair, got {type(ab).__name__}")
        # host-side weight normalization (numpy in, device out): no
        # device read happens here
        a = np.asarray(a_raw, np.float32)
        b = np.asarray(b_raw, np.float32)
        L = self.num_layers
        d_in, d_out = self.shapes[t]
        if a.ndim == 2:
            a = np.broadcast_to(a, (L,) + a.shape)
        if b.ndim == 2:
            b = np.broadcast_to(b, (L,) + b.shape)
        if a.ndim != 3 or a.shape[0] != L or a.shape[2] != d_in:
            raise ValueError(
                f"adapter {name!r} target {t!r}: A must be "
                f"[r, {d_in}] or [{L}, r, {d_in}], got "
                f"{tuple(np.asarray(a_raw).shape)}")
        r_a = a.shape[1]
        if r_a < 1 or r_a > self.rank:
            raise ValueError(
                f"adapter {name!r} target {t!r}: rank {r_a} exceeds "
                f"the bank rank {self.rank} (or is < 1)")
        if b.ndim != 3 or b.shape != (L, d_out, r_a):
            raise ValueError(
                f"adapter {name!r} target {t!r}: B must be "
                f"[{d_out}, {r_a}] or [{L}, {d_out}, {r_a}] to match "
                f"A's rank, got {tuple(np.asarray(b_raw).shape)}")
        scale = 1.0 if alpha is None else float(alpha) / r_a
        b = b * scale
        if r_a < self.rank:
            # zero-padded rank rows contribute exactly 0 to the delta
            a = np.concatenate(
                [a, np.zeros((L, self.rank - r_a, d_in), np.float32)],
                axis=1)
            b = np.concatenate(
                [b, np.zeros((L, d_out, self.rank - r_a), np.float32)],
                axis=2)
        return (jnp.asarray(a, self.dtype), jnp.asarray(b, self.dtype))

    def unload(self, name: str) -> bool:
        """Unload an adapter. Returns True when the index freed NOW;
        False when live slots still reference it — the unload DEFERS:
        the name leaves the registry immediately (new requests naming
        it are rejected) and the index frees when the last live
        reference releases. Never corrupts a live slot: the bank rows
        stay untouched until the index is recycled by a future load."""
        idx = self._names.get(name)
        if idx is None:
            raise ValueError(f"adapter {name!r} is not loaded")
        del self._names[name]
        if monitor.enabled():
            self._resident_gauge().labels(engine=self._engine).set(
                len(self._names))
        if self._refs.get(idx, 0) > 0:
            self._draining.add(idx)
            if trace.enabled():
                trace.event("lora.unload", adapter=name, index=idx,
                            deferred=True, refs=self._refs[idx],
                            engine=self._engine)
            return False
        self._free_index(idx)
        if trace.enabled():
            trace.event("lora.unload", adapter=name, index=idx,
                        deferred=False, engine=self._engine)
        return True

    def _free_index(self, idx: int) -> None:
        self._name_of.pop(idx, None)
        self._salt.pop(idx, None)
        self._refs.pop(idx, None)
        self._draining.discard(idx)
        self._free.append(idx)
        self._free.sort()

    # -- per-request references (admission / retirement) ---------------------
    def acquire(self, name: str) -> int:
        """Resolve ``name`` to its bank index and take one live
        reference (one admitted request). Raises ValueError for an
        unknown name or one mid-unload — a REQUEST-scoped verdict (the
        admission seam fails that request; the engine is untouched)."""
        idx = self._names.get(name)
        if idx is None:
            raise ValueError(
                f"unknown adapter {name!r} (resident: "
                f"{sorted(self._names) or 'none'})")
        self._refs[idx] = self._refs.get(idx, 0) + 1
        if monitor.enabled():
            self._requests_counter().labels(
                engine=self._engine, adapter=name).inc()
        return idx

    def release(self, idx: int) -> None:
        """Drop one live reference (the request retired/cancelled/
        preempted). Completes a deferred unload when the last reference
        goes."""
        if idx == 0 or idx not in self._refs:
            return
        self._refs[idx] -= 1
        if self._refs[idx] <= 0 and idx in self._draining:
            name = self._name_of.get(idx)
            self._free_index(idx)
            if trace.enabled():
                trace.event("lora.unload", adapter=name, index=idx,
                            deferred=False, engine=self._engine)

    def release_all(self) -> None:
        """Drop EVERY live reference (engine ``reset_state``: all slots
        were just forgotten wholesale). Deferred unloads complete; the
        bank and the name map survive — adapters are weights, and a
        supervised restart must not lose them."""
        for idx in list(self._refs):
            self._refs[idx] = 0
            if idx in self._draining:
                self._free_index(idx)

    # -- lookups (atomic reads; safe cross-thread) ---------------------------
    def __contains__(self, name) -> bool:
        return name in self._names

    def salt(self, idx: int) -> bytes:
        """Prefix-cache chain salt for bank index ``idx`` (b"" for the
        base model — base hashes keep their pre-LoRA values, so a
        LoRA-enabled engine's base traffic still warm-hits KV cached
        before any adapter existed)."""
        return self._salt.get(idx, b"")

    def resident(self) -> dict:
        """Host-side registry snapshot for ``engine.load()``/healthz:
        ``{"capacity", "resident", "free", "adapters": [names...],
        "draining": [names...]}``. Runs on CROSS-thread readers (an
        HTTP healthz thread, the router's affinity probe), so every
        container is snapshotted atomically (list()/tuple() of the
        live dict/set) before iteration — the scheduler thread may
        mutate mid-call and a live-set iterator would raise."""
        names = list(self._names)
        name_of = dict(self._name_of)
        return {
            "capacity": self.capacity,
            "resident": len(names),
            "free": len(self._free),
            "adapters": sorted(names),
            "draining": sorted(name_of[i] for i in tuple(self._draining)
                               if i in name_of),
        }

    # -- warmup / monitor ----------------------------------------------------
    def warmup(self) -> None:
        """Pre-compile the per-target row-install programs (a
        value-neutral zero write into base row 0) so the first hot
        ``load`` in a serving gap never pays an XLA compile."""
        for t in self.targets:
            A, B = self.bank[t]
            L = self.num_layers
            d_in, d_out = self.shapes[t]
            self.bank[t] = self._install(
                A, B, jnp.zeros((L, self.rank, d_in), self.dtype),
                jnp.zeros((L, d_out, self.rank), self.dtype),
                jnp.int32(0))

    @staticmethod
    def _requests_counter():
        return monitor.counter(
            "paddle_tpu_lora_requests_total",
            "requests admitted per engine and adapter (adapter = the "
            "fine-tune the request decoded under)",
            ("engine", "adapter"))

    @staticmethod
    def _resident_gauge():
        return monitor.gauge(
            "paddle_tpu_lora_adapters_resident",
            "LoRA adapters currently resident in the engine's device "
            "bank", ("engine",))

    def close(self) -> None:  # lint: retires-series
        """Retire this registry's monitor series (idempotent; the
        adapter label dimension is open-ended, so retire by engine
        label)."""
        for name in ("paddle_tpu_lora_requests_total",
                     "paddle_tpu_lora_adapters_resident"):
            try:
                monitor.remove_series(name, engine=self._engine)
            except Exception:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
