"""SLO-driven overload control plane: the observe→act loop, host-side.

PR 15 built the senses — per-tenant goodput, fast/slow burn-rate
windows, merge-exact fleet digests — and PR 13/9/14 built the muscles
— per-tenant quotas, drain/rolling-restart, ``ReplicaSpec``. Nothing
connected them: under sustained overload the stack admits until the
queue rejects, and one hot tenant burns every tenant's error budget.
This module is the connection, three escalating actuators that each
consume signals that already exist and move levers that already exist:

- **Burn-rate admission control** (:meth:`ControlPlane.tick` →
  scheduler submit path): when a tenant's FAST burn window fires
  (``burn_fast >= shed_burn`` with at least ``shed_min_count`` scored
  requests — one unlucky request must not shed a tenant), new submits
  for that tenant are rejected with ``RequestRejected("shed")``
  carrying a ``retry_after_s`` derived from the burn window (HTTP 429
  + ``Retry-After``), and entries ALREADY queued are deprioritized
  into the queue's penalty band rather than dropped — admitted work is
  never degraded, queued work yields to other tenants, new work waits
  out the window.
- **Brownout ladder** (:attr:`ControlPlane.rung`): a fleet-wide
  ordered degradation ladder driven by queue occupancy (and forced to
  at least rung 1 by any tenant burning hot) —

      rung 1: tighten per-tenant quotas (effective cap halves)
      rung 2: cap ``max_new_tokens`` on FUTURE admissions
      rung 3: disable speculative decoding on FUTURE admissions
      rung 4: pause prefix-cache admission (no new CoW/shared pages)

  Engagement is immediate (overload is urgent: the ladder can jump
  several rungs in one tick); DISENGAGEMENT is hysteretic — one rung
  at a time, only after occupancy drops ``rung_hysteresis`` below the
  rung's engage threshold AND the rung has been held ``rung_dwell_s``
  (a load oscillating around a threshold must not flap the ladder).
  Every transition is traced (``control.rung``) and visible in
  ``/healthz``. All four rungs are host-side decisions about FUTURE
  admissions: already-admitted requests keep their exact
  configuration, so rung transitions are bitwise-neutral for running
  greedy streams and no rung compiles a new program.
- **Elastic fleet** (:class:`ElasticController` → router supervisor
  tick): grow/shrink the replica count from queue depth per routable
  replica (+ fleet burn). Decisions are rate-limited (one scale event
  per ``scale_cooldown_s``) and hysteretic (``scale_signals``
  CONSECUTIVE agreeing ticks required), and scale-down always drains —
  PR 9's bar: never fail an in-flight handle.

Everything here is plain host arithmetic on snapshot dicts: zero
device work, zero new compiled programs, deterministic under an
explicit ``now`` (the flap-resistance tests drive synthetic clocks
through the same code paths production uses).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["ControlPolicy", "ControlPlane", "ElasticController"]

# brownout ladder size (rungs 1..N_RUNGS; 0 = fully disengaged)
N_RUNGS = 4
RUNG_ACTIONS = ("off", "quota_tighten", "max_new_cap", "spec_off",
                "prefix_pause")


class ControlPolicy:
    """Thresholds + rate limits for the whole control plane.

    One policy object configures all three actuators so a deployment
    tunes overload behavior in one place; the server consumes the shed
    / brownout knobs, the router the elastic ones. Defaults are sized
    for the CPU-tiny bench fixtures — a real deployment should derive
    them from its SLO policy and fleet size."""

    def __init__(self, *,
                 shed_burn: float = 2.0,
                 shed_min_count: int = 8,
                 penalty_band: int = 8,
                 rung_up: Tuple[float, ...] = (0.5, 0.65, 0.8, 0.9),
                 rung_hysteresis: float = 0.15,
                 rung_dwell_s: float = 2.0,
                 brownout_max_new: int = 32,
                 tick_interval_s: float = 0.25,
                 scale_up_depth: float = 4.0,
                 scale_down_depth: float = 0.5,
                 scale_signals: int = 3,
                 scale_cooldown_s: float = 10.0):
        if not shed_burn > 0:
            raise ValueError(
                f"shed_burn must be > 0, got {shed_burn!r}")
        if shed_min_count < 1:
            raise ValueError(
                f"shed_min_count must be >= 1, got {shed_min_count!r}")
        if penalty_band < 1:
            raise ValueError(
                f"penalty_band must be >= 1, got {penalty_band!r}")
        if len(rung_up) != N_RUNGS:
            raise ValueError(
                f"rung_up needs {N_RUNGS} engage thresholds "
                f"(one per rung), got {rung_up!r}")
        if list(rung_up) != sorted(rung_up) or not rung_up[0] > 0:
            raise ValueError(
                f"rung_up thresholds must be positive and "
                f"non-decreasing, got {rung_up!r}")
        if not rung_hysteresis > 0:
            raise ValueError(
                f"rung_hysteresis must be > 0, got {rung_hysteresis!r}")
        if not rung_dwell_s >= 0:
            raise ValueError(
                f"rung_dwell_s must be >= 0, got {rung_dwell_s!r}")
        if brownout_max_new < 1:
            raise ValueError(
                f"brownout_max_new must be >= 1, got "
                f"{brownout_max_new!r}")
        if not tick_interval_s >= 0:
            raise ValueError(
                f"tick_interval_s must be >= 0, got "
                f"{tick_interval_s!r}")
        if not scale_up_depth > scale_down_depth >= 0:
            raise ValueError(
                f"need scale_up_depth > scale_down_depth >= 0, got "
                f"{scale_up_depth!r}/{scale_down_depth!r}")
        if scale_signals < 1:
            raise ValueError(
                f"scale_signals must be >= 1, got {scale_signals!r}")
        if not scale_cooldown_s >= 0:
            raise ValueError(
                f"scale_cooldown_s must be >= 0, got "
                f"{scale_cooldown_s!r}")
        self.shed_burn = float(shed_burn)
        self.shed_min_count = int(shed_min_count)
        self.penalty_band = int(penalty_band)
        self.rung_up = tuple(float(v) for v in rung_up)
        self.rung_hysteresis = float(rung_hysteresis)
        self.rung_dwell_s = float(rung_dwell_s)
        self.brownout_max_new = int(brownout_max_new)
        self.tick_interval_s = float(tick_interval_s)
        self.scale_up_depth = float(scale_up_depth)
        self.scale_down_depth = float(scale_down_depth)
        self.scale_signals = int(scale_signals)
        self.scale_cooldown_s = float(scale_cooldown_s)


class ControlPlane:
    """Per-server control state: shed windows + the brownout ladder.

    Driven from the scheduler's inter-segment gap (:meth:`tick`, which
    rate-limits itself to ``tick_interval_s``) and read from the
    submit path (:meth:`shed_check`) and the admission path
    (:attr:`rung`, :meth:`degrade_cfg`). All state is host dicts under
    one small lock — reads never wait on engine work, matching the
    ``Server.load()`` promise."""

    def __init__(self, policy: ControlPolicy, *,
                 fast_window_s: float = 60.0):
        if not isinstance(policy, ControlPolicy):
            raise ValueError(
                f"policy must be a ControlPolicy, got {policy!r}")
        self.policy = policy
        self.fast_window_s = float(fast_window_s)
        self._lock = threading.Lock()
        self.rung = 0                     # guarded-by: self._lock
        self._rung_since = -1e18          # guarded-by: self._lock
        self._shed_until: Dict[str, float] = {}  # guarded-by: _lock
        # lifetime shed counts per (tenant, reason) — the /healthz and
        # monitor-series source of truth
        self._shed_counts: Dict[Tuple[str, str], int] = {}
        self._last_tick = -1e18           # guarded-by: self._lock

    # -- submit-path reads ---------------------------------------------------
    def shed_check(self, tenant: Optional[str],
                   now: float) -> Optional[float]:
        """``retry_after_s`` when ``tenant`` is inside an active shed
        window (the submit path turns it into a 429), else None.
        Expired windows clear lazily here as well as in :meth:`tick`,
        so a quiet server un-sheds without waiting for a gap."""
        if tenant is None:
            return None
        with self._lock:
            until = self._shed_until.get(tenant)
            if until is None:
                return None
            if now >= until:
                del self._shed_until[tenant]
                return None
            return until - now

    def note_shed(self, tenant: str, reason: str) -> int:
        """Count one shed rejection; returns the tenant's new total
        (over every reason) for the storm detector."""
        with self._lock:
            key = (tenant, reason)
            self._shed_counts[key] = self._shed_counts.get(key, 0) + 1
            return sum(n for (t, _), n in self._shed_counts.items()
                       if t == tenant)

    # -- admission-path reads ------------------------------------------------
    def degrade_cfg(self, cfg):
        """Apply the active brownout rungs to a request ABOUT TO BE
        ADMITTED: rung >= 2 caps ``max_new_tokens``, rung >= 3 forces
        speculative decoding off. Returns ``cfg`` unchanged below rung
        2 (the common case allocates nothing); a degraded request gets
        a fresh config copy, so the client's object — and every
        already-admitted request — is never mutated."""
        with self._lock:
            rung = self.rung
        if rung < 2:
            return cfg
        kw = dict(vars(cfg))
        if rung >= 2:
            kw["max_new_tokens"] = min(int(kw["max_new_tokens"]),
                                       self.policy.brownout_max_new)
        if rung >= 3:
            kw["speculative"] = False
        return type(cfg)(**kw)

    def quota_cap(self, cap: int) -> int:
        """Rung >= 1 tightens a tenant's effective admission quota to
        half (floor 1) — queued work from every tenant keeps moving,
        just narrower."""
        with self._lock:
            rung = self.rung
        if rung >= 1:
            return max(1, cap // 2)
        return cap

    # -- the control tick (scheduler gap) ------------------------------------
    def tick(self, now: float, *, queue_depth: int, max_queue: int,
             tenant_stats: Optional[Dict[str, Dict[str, Any]]]
             ) -> Optional[Dict[str, Any]]:
        """One control decision pass. Returns None when rate-limited
        (< ``tick_interval_s`` since the last pass), else a decision
        dict the caller actuates (traces, metrics, queue penalties):

        ``{"shed": [(tenant, until), ...], "unshed": [tenants...],
        "rung": new, "prev_rung": old, "occupancy": float}``

        Shedding: any tenant whose fast burn crossed ``shed_burn``
        (with enough scored requests) gets a shed window one fast-burn
        window long from NOW — re-firing while hot keeps extending it.
        Ladder: occupancy = queue_depth / max_queue engages rungs
        immediately; disengage is one rung per dwell with hysteresis.
        """
        pol = self.policy
        with self._lock:
            if now - self._last_tick < pol.tick_interval_s:
                return None
            self._last_tick = now
            out: Dict[str, Any] = {"shed": [], "unshed": [],
                                   "prev_rung": self.rung}
            # -- burn-rate shed windows
            burn_max = 0.0
            for tenant, rec in (tenant_stats or {}).items():
                burn = rec.get("burn_fast")
                if burn is None:
                    continue
                scored = int(rec.get("met", 0)) + int(
                    rec.get("missed", 0))
                burn_max = max(burn_max, burn)
                if burn >= pol.shed_burn \
                        and scored >= pol.shed_min_count:
                    until = now + self.fast_window_s
                    if tenant not in self._shed_until:
                        out["shed"].append((tenant, until))
                    self._shed_until[tenant] = until
            for tenant in [t for t, u in self._shed_until.items()
                           if now >= u]:
                del self._shed_until[tenant]
                out["unshed"].append(tenant)
            # -- brownout ladder
            occ = (queue_depth / max_queue) if max_queue > 0 else 0.0
            sig = occ
            if burn_max >= pol.shed_burn:
                # any tenant burning hot forces at least rung 1 even
                # with a shallow queue (latency overload, not depth)
                sig = max(sig, pol.rung_up[0])
            target = 0
            for i, thr in enumerate(pol.rung_up):
                if sig >= thr:
                    target = i + 1
            if target > self.rung:
                self.rung = target        # engage immediately
                self._rung_since = now
            elif self.rung > 0:
                down_thr = (pol.rung_up[self.rung - 1]
                            - pol.rung_hysteresis)
                if sig < down_thr \
                        and now - self._rung_since >= pol.rung_dwell_s:
                    self.rung -= 1        # disengage one rung at a time
                    self._rung_since = now
            out["rung"] = self.rung
            out["occupancy"] = round(occ, 4)
            return out

    # -- observability -------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The ``/healthz`` ``control`` block: active rung (+ its
        action name), per-tenant shed counts by reason, and the
        tenants currently inside a shed window."""
        with self._lock:
            sheds: Dict[str, Dict[str, int]] = {}
            for (tenant, reason), n in self._shed_counts.items():
                sheds.setdefault(tenant, {})[reason] = n
            return {"rung": self.rung,
                    "rung_action": RUNG_ACTIONS[self.rung],
                    "sheds": sheds,
                    "shed_active": sorted(self._shed_until)}


class ElasticController:
    """Deterministic scale decisions for the router's supervisor tick.

    Pure host arithmetic over fed signals with an explicit ``now`` —
    no clock reads, no I/O — so flap resistance is provable by driving
    a synthetic load trace through :meth:`decide`. Two guards make it
    flap-resistant by construction:

    - **hysteresis**: a scale verdict needs ``scale_signals``
      CONSECUTIVE agreeing ticks (any disagreeing tick resets the
      streak), so a load oscillating around a threshold never wins;
    - **rate limit**: at most one scale event per
      ``scale_cooldown_s``, regardless of how loud the signal is.

    The router actuates the returned delta: +1 builds/revives a
    replica, -1 drains one (never kills in-flight work — PR 9's bar).
    """

    def __init__(self, policy: ControlPolicy, *,
                 min_replicas: int = 1,
                 max_replicas: Optional[int] = None):
        if not isinstance(policy, ControlPolicy):
            raise ValueError(
                f"policy must be a ControlPolicy, got {policy!r}")
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas!r}")
        if max_replicas is not None and max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas must be >= min_replicas, got "
                f"{max_replicas!r} < {min_replicas!r}")
        self.policy = policy
        self.min_replicas = int(min_replicas)
        self.max_replicas = (None if max_replicas is None
                             else int(max_replicas))
        self._up_streak = 0
        self._down_streak = 0
        self._last_scale = -1e18

    def decide(self, now: float, *, routable: int, queue_depth: int,
               burn_max: float = 0.0) -> int:
        """One tick's verdict: +1 (scale up), -1 (scale down), or 0.
        Signals: queue depth per routable replica against the up/down
        thresholds, with any tenant burning past ``shed_burn`` forcing
        the up side (burn is latency overload the queue may not
        show)."""
        pol = self.policy
        per = queue_depth / max(1, routable)
        want_up = (per >= pol.scale_up_depth
                   or burn_max >= pol.shed_burn)
        want_down = (not want_up) and per <= pol.scale_down_depth
        if want_up:
            self._up_streak += 1
            self._down_streak = 0
        elif want_down:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
        if now - self._last_scale < pol.scale_cooldown_s:
            return 0
        if want_up and self._up_streak >= pol.scale_signals:
            if self.max_replicas is not None \
                    and routable >= self.max_replicas:
                return 0
            self._last_scale = now
            self._up_streak = 0
            return 1
        if want_down and self._down_streak >= pol.scale_signals \
                and routable > self.min_replicas:
            self._last_scale = now
            self._down_streak = 0
            return -1
        return 0


def max_burn(tenant_stats: Optional[Dict[str, Dict[str, Any]]],
             min_count: int = 1) -> float:
    """The hottest fast-burn rate across a tenant-stats table (0.0
    when nothing qualifies) — the fleet-level overload signal both the
    router's elastic tick and tests share."""
    out = 0.0
    for rec in (tenant_stats or {}).values():
        burn = rec.get("burn_fast")
        if burn is None:
            continue
        scored = int(rec.get("met", 0)) + int(rec.get("missed", 0))
        if scored >= min_count:
            out = max(out, burn)
    return out
