"""Stdlib HTTP front-end for :class:`~paddle_tpu.serving.Server`.

The reference exposes its inference capability through an RPC/HTTP
server above the predictor; this is our equivalent — intentionally
stdlib-only (``http.server``), because the serving story must work in
the bare container the engine runs in.

Routes:

- ``POST /generate`` — JSON body::

      {"prompt": [1, 2, 3],          # token ids (required)
       "max_new_tokens": 64, "temperature": 1.0, "top_k": 0,
       "top_p": 1.0, "do_sample": false, "eos_token_id": null,
       "seed": 0,                     # GenerationConfig fields
       "speculative": false, "draft_k": null,  # spec-decode opt-in
       "adapter": null,               # LoRA fine-tune (null = base)
       "tenant": null,                # quota bucket (default: adapter)
       "priority": 0, "timeout_s": null,   # admission deadline
       "stream": false}

  Bodies are STRICT: an unknown field is a 400 naming it — a typo'd
  ``adaptor`` must not silently serve base-model output.

  Non-streaming: one JSON response
  ``{"request_id", "tokens", "n_tokens", "ttft_s"}``.
  Streaming (``"stream": true``): chunked ``application/x-ndjson`` —
  one ``{"token": id}`` line per generated token AS IT ARRIVES (tokens
  reach the client segment-by-segment, long before completion), then a
  final ``{"done": true, "status": ..., "n_tokens": ...}`` line.

  Status codes are the backpressure contract: 400 malformed request
  (GenerationConfig validation / prompt that can never fit), 429 queue
  full OR tenant shed by the overload control plane — both with
  ``Retry-After`` (queue-depth-derived when full; the burn window's
  remaining life when shed — the body's ``retry_after_s`` float keeps
  the precision the integer header rounds up) — 503
  draining/degraded/shutdown, 504 admission deadline expired. A FAILED server (scheduler died) and a
  DEGRADED one (stalled step, mid-recovery) both reject immediately
  with 503 and a machine-readable ``reason``
  (``shutdown``/``degraded``) — a request must never queue into a
  server that may never drain it.

- ``GET /healthz`` — the server's ``load()`` snapshot, verbatim (ONE
  lock-light host-side read shared with the replica router):
  ``{"status": "warming"|"ok"|"degraded"|"draining"|"failed"
  |"stopped", "healthy", "queue_depth", "free_slots",
  "active_requests", "active_slots", "max_batch", "restarts"[,
  "free_pages", "total_pages", "occupancy"]}``. The HTTP code follows
  ``healthy``: 200 for "ok"/"draining", 503 otherwise — "warming" is
  the readiness gate (a ``Server(warmup=True)`` still pre-compiling —
  submissions already queue), "degraded" is the stall-watchdog /
  mid-recovery signal, "failed" means the scheduler died
  (``restarts`` counts supervised engine recoveries so far). Fronting
  a :class:`~paddle_tpu.serving.router.Router`, the same route serves
  the FLEET snapshot — per-replica states, circuit-breaker status,
  restart counts, flight-dump paths — and stays 200 while at least
  one replica routes (one dead replica degrades a fleet, it does not
  fail it). A paged engine adds ``"pressure"``:
  ``{"admission_mode", "occupancy", "free_pages",
  "waiting_on_pages", "preemptions"}`` — the KV memory-pressure
  surface that tells "degraded by memory pressure" (occupancy near
  1.0, preemptions climbing) apart from the stall/fault reason. A
  ``Server(control_policy=...)`` adds ``"control"``: ``{"rung",
  "rung_action", "sheds": {tenant: {reason: n}}, "shed_active"}`` —
  the active brownout rung and per-tenant shed counts; with
  the prefix cache on it also carries ``prefix_cache``,
  ``cached_pages``, ``shared_pages``, ``prefix_hits``,
  ``prefix_lookups``, and ``prefix_tokens_saved``.

- ``POST /adapters/load`` / ``POST /adapters/unload`` — multi-tenant
  LoRA admin (engines built with ``lora_capacity``): hot load (inline
  ``weights`` or an npz ``path``) / unload, applied by the scheduler
  thread in the inter-segment gap; an unload while live requests
  decode under the adapter DEFERS (``"deferred": true``). The
  registry snapshot (resident/draining names, capacity) rides
  ``/healthz`` under ``lora``.

- ``POST /kv/export`` / ``POST /kv/import`` — cross-process KV-page
  handoff (disaggregated prefill/decode; paged engines with
  ``prefix_cache=True``). Export takes ``{"tokens": [...]
  [, "salt": "<hex>"]}`` and returns the resident full-block pages
  covering the prompt's longest cached prefix as a framed
  octet-stream (length-prefixed JSON header — chain hashes, parents,
  tokens, dtype/geometry — followed by the raw page bytes: int8 rows
  ship WITH their per-page scales; a page copy, never a format
  conversion). Import takes the same framing and installs the pages
  into the pool + prefix index, chain-hash verified and idempotent on
  replay (resident blocks dedup). Both apply on the scheduler thread
  in the inter-segment gap.

- ``GET /metrics`` / ``GET /metrics.json`` — the monitor package's
  Prometheus / JSON exporters, same payloads as
  ``monitor.start_http_server`` (one scrape endpoint per serving
  process).

- ``GET /stats`` — the SLO/goodput rollup
  (``paddle_tpu.monitor.slo``): per-tenant goodput + fast/slow
  burn rates + token/KV-page-second cost, and per-(metric, tenant)
  latency percentiles (TTFT/TPOT/queue-wait/e2e) with an exact
  all-tenant ``"*"`` aggregate. Fronting a ``Router`` the same route
  serves the FLEET rollup — percentiles computed by MERGING replica
  digests (exact, never averaged), per-replica percentile blocks for
  the fleet-vs-replica comparison, and the skew detector's
  ``slow_replicas`` set. Render with
  ``tools/monitor_report.py --slo``.

- ``GET /trace?rid=N`` — one request's ordered lifecycle timeline
  (``paddle_tpu.tracing``; ``rid`` is the public ``request_id`` the
  ``/generate`` response carried): queue → admit (bucket) → segments →
  (preempt → replay …) → finish, as JSON event dicts. Without ``rid``
  returns the newest buffered events (bounded). 404 with a reason
  while ``FLAGS_enable_trace`` is off — there is no recorder to read.
  When the flight recorder has fired (engine fault / stall / preemption
  storm), ``/healthz`` carries the newest dump path as
  ``flight_dump``.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Optional

from .. import monitor
from .. import tracing as trace
from ..inference.generation import GenerationConfig
from .queue import (DeadlineExpired, RequestCancelled, RequestFailed,
                    RequestRejected)

__all__ = ["serve_http"]

_CFG_FIELDS = ("max_new_tokens", "temperature", "top_k", "top_p",
               "do_sample", "eos_token_id", "seed", "speculative",
               "draft_k", "adapter")

# every field a /generate body may carry. Unknown fields are a 400
# NAMING the field, not silently ignored: a typo'd "adaptor" quietly
# serving BASE-model output to a fine-tune's customer is the silent
# failure multi-tenant serving cannot afford
_KNOWN_FIELDS = frozenset(_CFG_FIELDS) | {"prompt", "priority",
                                          "timeout_s", "stream",
                                          "tenant", "idem_key",
                                          "from_token"}

# a /generate body is token ids + a dozen scalars; 8 MB is orders of
# magnitude above any real request, and an unbounded Content-Length
# would let one request buffer arbitrary bytes into the process that
# holds the model and KV pool
MAX_BODY_BYTES = 8 << 20

# a /kv/import body carries real page bytes (layers x pages x rows);
# still bounded — an unbounded Content-Length must not let a peer
# buffer arbitrary bytes into the serving process
MAX_KV_BODY_BYTES = 256 << 20


def _parse_request(body: dict):
    unknown = sorted(k for k in body if k not in _KNOWN_FIELDS)
    if unknown:
        raise ValueError(
            f"unknown request field {unknown[0]!r} (allowed: "
            f"{', '.join(sorted(_KNOWN_FIELDS))})")
    prompt = body.get("prompt")
    if (not isinstance(prompt, list) or not prompt
            or not all(isinstance(t, int) and not isinstance(t, bool)
                       and 0 <= t < 2**31 for t in prompt)):
        raise ValueError(
            "'prompt' must be a non-empty list of int32 token ids")
    cfg_kw = {k: body[k] for k in _CFG_FIELDS if k in body}
    try:
        cfg = GenerationConfig(**cfg_kw)
    except ValueError:
        raise
    except Exception as e:   # e.g. TypeError from a null/list field
        raise ValueError(f"bad GenerationConfig field: {e}") from e
    priority = body.get("priority", 0)
    if not isinstance(priority, int) or isinstance(priority, bool):
        raise ValueError(f"'priority' must be an int, got {priority!r}")
    timeout_s = body.get("timeout_s")
    if timeout_s is not None and (
            isinstance(timeout_s, bool)
            or not isinstance(timeout_s, (int, float))
            or not timeout_s > 0):
        raise ValueError(
            f"'timeout_s' must be a positive number or null, got "
            f"{timeout_s!r}")
    tenant = body.get("tenant")
    if tenant is not None and (not isinstance(tenant, str)
                               or not tenant):
        raise ValueError(
            f"'tenant' must be a non-empty string or null, got "
            f"{tenant!r}")
    stream = body.get("stream", False)
    if not isinstance(stream, bool):
        # the same silent-failure class as the typo'd "adaptor":
        # bool("false") is True, so a client sending the STRING
        # "false" would silently get a streamed response it cannot
        # parse — name the type error instead of coercing
        raise ValueError(
            f"'stream' must be a boolean, got {stream!r}")
    idem_key = body.get("idem_key")
    if idem_key is not None and (not isinstance(idem_key, str)
                                 or not idem_key):
        raise ValueError(
            f"'idem_key' must be a non-empty string or null, got "
            f"{idem_key!r}")
    from_token = body.get("from_token", 0)
    if (not isinstance(from_token, int) or isinstance(from_token, bool)
            or from_token < 0):
        raise ValueError(
            f"'from_token' must be a non-negative int, got "
            f"{from_token!r}")
    return (prompt, cfg, priority, timeout_s, stream, tenant,
            idem_key, from_token)


def _adapter_weights(body: dict) -> dict:
    """Normalize a /adapters/load body to the registry's params format
    ``{target: (A, B)}``: inline ``weights`` (nested lists) or an
    ``npz`` file ``path`` with ``<target>.a`` / ``<target>.b`` keys."""
    import numpy as np

    weights = body.get("weights")
    path = body.get("path")
    if (weights is None) == (path is None):
        raise ValueError(
            "exactly one of 'weights' (inline) or 'path' (npz file) "
            "is required")
    if path is not None:
        if not isinstance(path, str):
            raise ValueError(f"'path' must be a string, got {path!r}")
        data = np.load(path)
        out = {}
        for key in data.files:
            t, _, kind = key.rpartition(".")
            if kind not in ("a", "A", "b", "B") or not t:
                raise ValueError(
                    f"npz key {key!r} is not '<target>.a'/'<target>.b'")
            out.setdefault(t, [None, None])[0 if kind in ("a", "A")
                                            else 1] = data[key]
        bad = [t for t, ab in out.items() if ab[0] is None
               or ab[1] is None]
        if bad:
            raise ValueError(
                f"npz missing the a or b half for target(s) {bad}")
        return {t: (a, b) for t, (a, b) in out.items()}
    if not isinstance(weights, dict) or not weights:
        raise ValueError(
            "'weights' must be a non-empty object "
            "{target: {'a': [[...]], 'b': [[...]]}}")
    out = {}
    for t, ab in weights.items():
        if (not isinstance(ab, dict) or "a" not in ab
                or "b" not in ab):
            raise ValueError(
                f"weights[{t!r}] must be an object with 'a' and 'b' "
                "factor arrays")
        extra = sorted(k for k in ab if k not in ("a", "b"))
        if extra:
            raise ValueError(
                f"weights[{t!r}] has unknown key {extra[0]!r} "
                "(allowed: a, b)")
        out[t] = (np.asarray(ab["a"], np.float32),
                  np.asarray(ab["b"], np.float32))
    return out


def serve_http(server, port: int = 0, addr: str = "127.0.0.1",
               idem_ttl_s: float = 30.0, resume_grace_s: float = 2.0):
    """Serve ``server`` over HTTP on a daemon thread; returns the
    ``ThreadingHTTPServer`` (bound port: ``httpd.server_address[1]``;
    ``port=0`` picks a free one). Stop with ``httpd.shutdown()``.

    ``idem_ttl_s`` bounds the idempotency dedup window: a retried
    ambiguous ``/generate`` POST carrying the same ``idem_key``
    attaches to the live request (or its cached terminal result)
    instead of admitting twice; terminal entries are pruned this many
    seconds after finishing. ``resume_grace_s`` is how long a stream
    whose client tore away keeps DECODING before the slot is
    reclaimed — the window a mid-stream resume (same ``idem_key`` +
    ``from_token``) must land in to keep warm KV and skip
    re-prefill."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    import numpy as np

    # the exactly-once window: idem_key -> {"handle", "orphaned_at"}.
    # Closure-scoped (one window per front, like the Handler class
    # itself); all access under idem_lock. ``orphaned_at`` non-None
    # means the streaming client tore away and the request is decoding
    # unattended — resumable until the grace expires, cancelled after.
    idem_lock = threading.Lock()
    idem_window = {}
    wire_stats = {"idem_attaches": 0, "integrity_rejects": 0,
                  "resume_misses": 0}

    def _prune_idem(now: float) -> None:
        expired = []
        with idem_lock:
            for key in list(idem_window):
                ent = idem_window[key]
                h = ent["handle"]
                if h.done:
                    fin = getattr(h, "finish_ts", None)
                    if fin is None or now - fin > idem_ttl_s:
                        del idem_window[key]
                elif (ent["orphaned_at"] is not None
                        and now - ent["orphaned_at"] > resume_grace_s):
                    # no resume came: stop burning the slot
                    del idem_window[key]
                    expired.append(h)
        for h in expired:                 # cancel outside the lock
            h.cancel()

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # -- helpers ---------------------------------------------------------
        def _json(self, code: int, obj: dict,
                  headers: Optional[dict] = None) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _chunk(self, data: bytes) -> None:
            self.wfile.write(f"{len(data):X}\r\n".encode())
            self.wfile.write(data)
            self.wfile.write(b"\r\n")
            self.wfile.flush()

        # -- routes ----------------------------------------------------------
        def do_GET(self):
            if self.path.startswith("/healthz"):
                # ONE host-side snapshot serves both a single Server
                # and a Router fleet: ``load()`` carries status, queue
                # depth, slot/page capacity, the KV-pressure block, the
                # newest flight-recorder dump path — and, for a Router,
                # the per-replica states + circuit-breaker status. The
                # ``healthy`` verdict inside it decides 200 vs 503
                # (Server: status ok/draining; Router: >= 1 routable
                # replica — a fleet with one dead replica still takes
                # traffic, and its healthz still names the casualty).
                body = server.load()
                healthy = body.get(
                    "healthy", body.get("status") in ("ok", "draining"))
                body["wire"] = dict(wire_stats)
                hdrs = None
                if not healthy and body.get("status") == "warming":
                    # Retry-After parity: warmup is bounded (segment
                    # sweep), so tell the client when to come back
                    # instead of letting it hammer the 503
                    body["retry_after_s"] = 1.0
                    hdrs = {"Retry-After": "1"}
                self._json(200 if healthy else 503, body,
                           headers=hdrs)
            elif self.path.startswith("/stats"):
                # SLO/goodput rollup (paddle_tpu.monitor.slo): a
                # Server serves its own tracker; a Router MERGES every
                # replica's digests — exact fleet percentiles (never
                # averaged), per-tenant goodput/burn from summed
                # counters, and the skew detector's slow set. Same
                # shape either way (tools/monitor_report.py --slo).
                # ``?shard=1`` instead returns the RAW digest shard
                # (``SLOTracker.digests_dict()``, to_dict-serialized
                # buckets and all): what a remote harvester feeds to
                # ``fleet_rollup`` — merging pre-rolled percentiles
                # would average, and fleet percentiles must merge.
                from urllib.parse import parse_qs, urlsplit

                q = parse_qs(urlsplit(self.path).query)
                if q.get("shard", ["0"])[0] not in ("0", ""):
                    slo = getattr(server, "slo", None)
                    if slo is None:
                        self._json(404, {
                            "error": "no digest shard: this front "
                                     "exposes no SLO tracker"})
                    else:
                        self._json(200, slo.digests_dict())
                    return
                fn = getattr(server, "stats", None)
                if fn is None:
                    self._json(404, {
                        "error": "no /stats: this front exposes no "
                                 "SLO tracker"})
                else:
                    self._json(200, fn())
            elif self.path.startswith("/profile"):
                # program-ledger roofline table (monitor.ledger): a
                # Server serves its engine's shard; a Router MERGES
                # every replica's shard exactly (same program id →
                # digests add bucketwise). Feed it to
                # tools/monitor_report.py --profile. Empty "programs"
                # (not a 404) while FLAGS_enable_ledger is off.
                fn = getattr(server, "profile", None)
                if fn is None:
                    self._json(404, {
                        "error": "no /profile: this front exposes no "
                                 "program ledger"})
                else:
                    self._json(200, fn())
            elif self.path.startswith("/trace"):
                self._trace_response()
            elif (payload := monitor.http_payload(self.path)) is not None:
                body, ctype = payload
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._json(404, {"error": f"no route {self.path}"})

        def _trace_response(self) -> None:
            from urllib.parse import parse_qs, urlsplit

            if not trace.enabled():
                self._json(404, {
                    "error": "tracing disabled — enable with "
                             "FLAGS_enable_trace=1 / "
                             "paddle_tpu.tracing.enable()"})
                return
            q = parse_qs(urlsplit(self.path).query)
            rid = q.get("rid", [None])[0]
            if rid is None:
                evs = trace.events(limit=256)
                self._json(200, {"events": evs, "n": len(evs)})
                return
            try:
                rid_i = int(rid)
            except ValueError:
                self._json(400, {"error": f"rid must be an int "
                                          f"request id, got {rid!r}"})
                return
            self._json(200, {
                "request_id": rid_i,
                "events": server.request_timeline(rid_i)})

        def _read_body(self):
            """Bounded JSON body read shared by the POST routes;
            returns the dict or None after replying with the error."""
            n = int(self.headers.get("Content-Length", 0))
            if n < 0:
                # rfile.read(-1) would block until the client closes
                # the socket, pinning a handler thread
                self.close_connection = True
                self._json(400, {"error": "negative Content-Length"},
                           headers={"Connection": "close"})
                return None
            if n > MAX_BODY_BYTES:
                self.close_connection = True
                self._json(413, {"error":
                                 f"body exceeds {MAX_BODY_BYTES} "
                                 "bytes"},
                           headers={"Connection": "close"})
                return None
            body = json.loads(self.rfile.read(n) or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            return body

        def do_POST(self):
            if self.path.startswith("/adapters/"):
                self._adapters_response()
                return
            if self.path.startswith("/kv/"):
                self._kv_response()
                return
            if not self.path.startswith("/generate"):
                # body NOT consumed: drop the connection after replying
                # or keep-alive would parse the body as the next request
                self.close_connection = True
                self._json(404, {"error": f"no route {self.path}"},
                           headers={"Connection": "close"})
                return
            try:
                body = self._read_body()
                if body is None:
                    return
                (prompt, cfg, priority, timeout_s, stream, tenant,
                 idem_key, from_token) = _parse_request(body)
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._json(400, {"error": str(e)})
                return
            _prune_idem(time.monotonic())
            if idem_key is not None:
                with idem_lock:
                    ent = idem_window.get(idem_key)
                    if ent is not None:
                        ent["orphaned_at"] = None   # reattached
                if ent is not None:
                    # the exactly-once attach: this POST is a retry of
                    # a request this server ALREADY holds (live or
                    # terminal within the TTL) — no second admission,
                    # no second slot/pages, no double SLO/quota count.
                    # The response carries the SAME request_id, which
                    # is how clients (and the dedup regression test)
                    # prove single admission.
                    wire_stats["idem_attaches"] += 1
                    handle = ent["handle"]
                    if trace.enabled():
                        trace.event("idem.attach", rid=handle.id,
                                    from_token=from_token,
                                    live=not handle.done)
                    if stream:
                        self._stream_response(handle, skip=from_token,
                                              idem=idem_key)
                    else:
                        self._block_response(handle)
                    return
                if from_token > 0:
                    # a resume aimed at a request we no longer (or
                    # never) held — refuse loudly so the client falls
                    # back to the failover replay, never a silent
                    # fresh decode that would double-emit tokens
                    wire_stats["resume_misses"] += 1
                    self._json(409, {"error": "unknown idem_key for "
                                              "mid-stream resume",
                                     "reason": "resume_miss"})
                    return
            try:
                handle = server.submit(
                    np.asarray(prompt, np.int32), cfg,
                    priority=priority, timeout_s=timeout_s,
                    **({"tenant": tenant} if tenant is not None
                       else {}))
            except RequestRejected as e:
                if e.reason in ("queue_full", "shed"):
                    # both are 429 backpressure, with honest hints:
                    # a SHED tenant's Retry-After is its burn window's
                    # remaining life (retrying sooner just re-rejects);
                    # a full queue's is depth-derived (deeper backlog
                    # -> back off longer). The body carries the float
                    # (retry_after_s) so programmatic clients — and
                    # RemoteReplica, which re-raises with it — keep
                    # the precision the integer header rounds away.
                    ra = e.retry_after_s
                    if ra is None:   # queue_full: scale with backlog
                        try:
                            depth = server.queue.depth
                        except Exception:
                            depth = 0
                        ra = 1.0 + depth / 8.0
                    ra = max(0.0, float(ra))
                    self._json(429, {"error": str(e),
                                     "reason": e.reason,
                                     "retry_after_s": round(ra, 3)},
                               headers={"Retry-After":
                                        str(max(1, int(-(-ra // 1))))})
                else:   # draining / degraded / shutdown (failed server)
                    # Retry-After parity with the 429 paths: a DRAINING
                    # server knows its drain ETA and says so — the same
                    # honest hint, float body field + integer header
                    out = {"error": str(e), "reason": e.reason}
                    hdrs = None
                    if e.retry_after_s is not None:
                        ra = max(0.0, float(e.retry_after_s))
                        out["retry_after_s"] = round(ra, 3)
                        hdrs = {"Retry-After":
                                str(max(1, int(-(-ra // 1))))}
                    self._json(503, out, headers=hdrs)
                return
            except ValueError as e:   # can never fit the engine
                self._json(400, {"error": str(e)})
                return
            if idem_key is not None:
                with idem_lock:
                    idem_window[idem_key] = {"handle": handle,
                                             "orphaned_at": None}
            if stream:
                self._stream_response(handle, idem=idem_key)
            else:
                self._block_response(handle)

        def _kv_response(self) -> None:
            """Disaggregated prefill/decode page handoff: ``POST
            /kv/export`` ``{"tokens": [...][, "salt": "<hex>"]}``
            returns the resident full-block pages covering the prompt
            as a framed octet-stream (JSON header + raw page bytes —
            ``serving.remote.encode_kv_payload``); ``POST /kv/import``
            takes the same framing back and installs the pages into
            this server's pool + prefix index (chain-hash verified,
            idempotent on replay). Both apply on the scheduler thread
            in the inter-segment gap — the pools are donated by device
            writes and must never be read from a handler thread. 400
            for validation errors (strict bodies, geometry/dtype
            mismatch, corrupt chain hash), 503 while the scheduler
            cannot apply them."""
            op = self.path[len("/kv/"):].split("?", 1)[0]
            if op not in ("export", "import"):
                self.close_connection = True
                self._json(404, {"error": f"no route {self.path}"},
                           headers={"Connection": "close"})
                return
            if (getattr(server, "export_kv", None) is None
                    or not getattr(getattr(server, "engine", None),
                                   "prefix_cache", False)):
                # permanently unsupported here (a Router front, or an
                # engine without the paged prefix cache) — a 400, not
                # a retryable 503
                self.close_connection = True
                self._json(400, {"error": "this endpoint fronts no "
                                          "KV-handoff-capable Server "
                                          "(needs a paged engine with "
                                          "prefix_cache=True)"},
                           headers={"Connection": "close"})
                return
            from .remote import (KVIntegrityError, decode_kv_payload,
                                 encode_kv_payload)
            try:
                if op == "export":
                    body = self._read_body()
                    if body is None:
                        return
                    # strict like /generate: a typo'd "token" must not
                    # silently export an empty prefix
                    allowed = {"tokens", "salt"}
                    unknown = sorted(k for k in body
                                     if k not in allowed)
                    if unknown:
                        raise ValueError(
                            f"unknown field {unknown[0]!r} (allowed: "
                            f"{', '.join(sorted(allowed))})")
                    tokens = body.get("tokens")
                    if (not isinstance(tokens, list) or not tokens
                            or not all(isinstance(t, int)
                                       and not isinstance(t, bool)
                                       and 0 <= t < 2**31
                                       for t in tokens)):
                        raise ValueError(
                            "'tokens' must be a non-empty list of "
                            "int32 token ids")
                    salt = body.get("salt", "")
                    if not isinstance(salt, str):
                        raise ValueError(
                            f"'salt' must be a hex string, got "
                            f"{salt!r}")
                    payload = server.export_kv(
                        np.asarray(tokens, np.int32),
                        salt=bytes.fromhex(salt))
                    raw = encode_kv_payload(payload)
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header("Content-Length", str(len(raw)))
                    self.end_headers()
                    self.wfile.write(raw)
                    return
                n = int(self.headers.get("Content-Length", 0))
                if n <= 0 or n > MAX_KV_BODY_BYTES:
                    self.close_connection = True
                    self._json(
                        400 if n <= 0 else 413,
                        {"error": ("missing/empty body"
                                   if n <= 0 else
                                   f"body exceeds {MAX_KV_BODY_BYTES}"
                                   f" bytes")},
                        headers={"Connection": "close"})
                    return
                out = server.import_kv(
                    decode_kv_payload(self.rfile.read(n)))
            except KVIntegrityError as e:
                # checksum mismatch: the decode raised BEFORE
                # ``import_kv`` ran, so nothing installed — typed so
                # the shipper can count it and re-ship (idempotent)
                wire_stats["integrity_rejects"] += 1
                if trace.enabled():
                    trace.event("kv.integrity_reject", error=str(e))
                self._json(400, {"error": str(e),
                                 "reason": "integrity"})
                return
            except (ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._json(400, {"error": str(e)})
                return
            except (TimeoutError, RequestRejected,
                    RuntimeError) as e:
                # transient: the scheduler could not apply it right
                # now (wedged / shutting down)
                self._json(503, {"error": str(e)})
                return
            self._json(200, out)

        def _adapters_response(self) -> None:
            """Admin surface for multi-tenant LoRA: ``POST
            /adapters/load`` ``{"name": ..., "weights": {target:
            {"a": [[...]], "b": [[...]]}}[, "alpha": N]}`` (or
            ``{"name": ..., "path": "adapter.npz"}`` with
            ``<target>.a`` / ``<target>.b`` arrays) and ``POST
            /adapters/unload`` ``{"name": ...}``. Applied by the
            scheduler thread in the inter-segment gap; 400 for
            validation errors (unknown target, rank over the bank,
            duplicate name, registry full), 503 while the server
            cannot apply them. Registry state lives in ``/healthz``
            under ``lora``."""
            op = self.path[len("/adapters/"):].split("?", 1)[0]
            if op not in ("load", "unload"):
                self.close_connection = True
                self._json(404, {"error": f"no route {self.path}"},
                           headers={"Connection": "close"})
                return
            if (getattr(server, "load_adapter", None) is None
                    or getattr(getattr(server, "engine", None),
                               "adapters", None) is None):
                # permanently unsupported here (a Router front, or an
                # engine built without lora_capacity) — a 400, not a
                # retryable 503
                self.close_connection = True
                self._json(400, {"error": "this endpoint fronts no "
                                          "adapter-capable Server "
                                          "(engine needs "
                                          "lora_capacity > 0)"},
                           headers={"Connection": "close"})
                return
            try:
                body = self._read_body()
                if body is None:
                    return
                # admin bodies are STRICT like /generate: a typo'd
                # "aplha" silently installing scale-1.0 deltas is the
                # same silent-failure class as the typo'd "adaptor"
                allowed = ({"name"} if op == "unload"
                           else {"name", "weights", "path", "alpha"})
                unknown = sorted(k for k in body if k not in allowed)
                if unknown:
                    raise ValueError(
                        f"unknown field {unknown[0]!r} (allowed: "
                        f"{', '.join(sorted(allowed))})")
                name = body.get("name")
                if not isinstance(name, str) or not name:
                    raise ValueError(
                        "'name' must be a non-empty string")
                if op == "unload":
                    freed = server.unload_adapter(name)
                    out = {"name": name, "unloaded": bool(freed),
                           "deferred": not freed}
                else:
                    params = _adapter_weights(body)
                    idx = server.load_adapter(name, params,
                                              alpha=body.get("alpha"))
                    out = {"name": name, "index": idx}
            except (ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._json(400, {"error": str(e)})
                return
            except (TimeoutError, RequestRejected, RuntimeError) as e:
                # transient: the scheduler could not apply it right
                # now (wedged / shutting down)
                self._json(503, {"error": str(e)})
                return
            reg = getattr(server.engine, "adapters", None)
            if reg is not None:
                out["adapters"] = reg.resident()
            self._json(200, out)

        def _block_response(self, handle) -> None:
            try:
                toks = handle.result()
            except DeadlineExpired as e:
                self._json(504, {"error": str(e), "request_id": handle.id})
                return
            except (RequestCancelled, RequestFailed) as e:
                self._json(500, {"error": str(e), "request_id": handle.id})
                return
            ttft = (None if handle.first_token_ts is None
                    else handle.first_token_ts - handle.submit_ts)
            self._json(200, {"request_id": handle.id,
                             "tokens": [int(t) for t in toks],
                             "n_tokens": len(toks), "ttft_s": ttft})

        def _stream_response(self, handle, skip: int = 0,
                             idem: Optional[str] = None) -> None:
            # the status line is deferred until the FIRST token (or a
            # terminal state) exists: a request that expires or fails
            # before emitting anything still gets its real 504/500,
            # not a 200 that then apologizes in the trailer
            it = handle.stream()
            first = None
            try:
                # a mid-stream resume already delivered the first
                # ``skip`` tokens on the torn connection: replay only
                # the tail (the handle's stream is re-iterable from 0
                # by design — each consumer keeps its own cursor)
                for _ in range(skip):
                    next(it)
                first = next(it)
            except StopIteration:
                pass              # zero-token terminal (e.g. cancelled)
            except DeadlineExpired as e:
                self._json(504, {"error": str(e),
                                 "request_id": handle.id})
                return
            except RequestFailed as e:
                self._json(500, {"error": str(e),
                                 "request_id": handle.id})
                return
            n = 0
            status = "finished"
            try:
                # header writes sit INSIDE the broken-pipe guard: a
                # client that disconnected while waiting for its first
                # token must trigger the cancel below, not strand a
                # decoding slot behind an unhandled socket error
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                if first is not None:
                    self._chunk(json.dumps({"token": int(first)})
                                .encode() + b"\n")
                    n += 1
                    for tok in it:
                        self._chunk(json.dumps({"token": int(tok)})
                                    .encode() + b"\n")
                        n += 1
                if handle.status == "cancelled":
                    status = "cancelled"
            except DeadlineExpired:
                status = "expired"
            except RequestFailed as e:
                status = f"failed: {e}"
            except (BrokenPipeError, ConnectionResetError):
                # client went away mid-stream. With an idem key the
                # request keeps DECODING for the resume grace period —
                # warm KV intact, so a reconnect replays only the tail;
                # the pruner cancels it if no resume comes. Without a
                # key: reclaim the slot immediately, as before.
                if idem is not None:
                    with idem_lock:
                        ent = idem_window.get(idem)
                        if ent is not None and not handle.done:
                            ent["orphaned_at"] = time.monotonic()
                            return
                handle.cancel()
                return
            try:
                self._chunk(json.dumps(
                    {"done": True, "status": status, "n_tokens": n,
                     "request_id": handle.id}).encode() + b"\n")
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                pass

        def log_message(self, *args):   # no access-log spam on stderr
            pass

    httpd = ThreadingHTTPServer((addr, port), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="paddle_tpu-serving-http")
    t.start()
    return httpd
