"""paddle.fft parity (reference: python/paddle/fft.py) — thin autograd-aware
wrappers over jnp.fft (XLA lowers these to the TPU FFT implementation)."""
from __future__ import annotations

import jax.numpy as jnp

from .core.autograd import apply_op

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
           "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _norm(norm):
    return None if norm in (None, "backward") else norm


def _wrap1(jfn, op):
    def f(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(lambda v: jfn(v, n=n, axis=axis, norm=_norm(norm)),
                        x, op_name=op)

    f.__name__ = op
    return f


def _wrap2(jfn, op):
    def f(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_op(lambda v: jfn(v, s=s, axes=axes, norm=_norm(norm)),
                        x, op_name=op)

    f.__name__ = op
    return f


def _wrapn(jfn, op):
    def f(x, s=None, axes=None, norm="backward", name=None):
        return apply_op(lambda v: jfn(v, s=s, axes=axes, norm=_norm(norm)),
                        x, op_name=op)

    f.__name__ = op
    return f


fft = _wrap1(jnp.fft.fft, "fft")
ifft = _wrap1(jnp.fft.ifft, "ifft")
rfft = _wrap1(jnp.fft.rfft, "rfft")
irfft = _wrap1(jnp.fft.irfft, "irfft")
hfft = _wrap1(jnp.fft.hfft, "hfft")
ihfft = _wrap1(jnp.fft.ihfft, "ihfft")
fft2 = _wrap2(jnp.fft.fft2, "fft2")
ifft2 = _wrap2(jnp.fft.ifft2, "ifft2")
rfft2 = _wrap2(jnp.fft.rfft2, "rfft2")
irfft2 = _wrap2(jnp.fft.irfft2, "irfft2")
fftn = _wrapn(jnp.fft.fftn, "fftn")
ifftn = _wrapn(jnp.fft.ifftn, "ifftn")
rfftn = _wrapn(jnp.fft.rfftn, "rfftn")
irfftn = _wrapn(jnp.fft.irfftn, "irfftn")


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    return apply_op(lambda v: jnp.fft.fftshift(v, axes=axes), x,
                    op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda v: jnp.fft.ifftshift(v, axes=axes), x,
                    op_name="ifftshift")


# hfft2/hfftn and inverses: jnp.fft lacks them; compose from the hermitian
# 1-D pair the same way the reference builds them from C2R/R2C kernels.
def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    def f(v):
        a0, a1 = axes
        # C2C on the leading axis FIRST, Hermitian C2R last (reference
        # fftn_c2r order) — the reversed order mixes the axes' symmetries
        # and the trailing .real would discard real information
        n0 = s[0] if s is not None else None
        v0 = jnp.fft.fft(v, n=n0, axis=a0, norm=_norm(norm))
        n1 = s[1] if s is not None else None
        return jnp.fft.hfft(v0, n=n1, axis=a1, norm=_norm(norm))

    return apply_op(f, x, op_name="hfft2")


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    def f(v):
        a0, a1 = axes
        # ihfft needs the REAL input: hermitian axis first, then ifft
        n1 = s[1] if s is not None else None
        v1 = jnp.fft.ihfft(v, n=n1, axis=a1, norm=_norm(norm))
        n0 = s[0] if s is not None else None
        return jnp.fft.ifft(v1, n=n0, axis=a0, norm=_norm(norm))

    return apply_op(f, x, op_name="ihfft2")


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    def f(v):
        ax = list(axes) if axes is not None else list(range(v.ndim))
        out = v
        if len(ax) > 1:
            # complex C2C on leading axes first (reference fftn_c2r order)
            rest_s = list(s[:-1]) if s is not None else None
            out = jnp.fft.fftn(out, s=rest_s, axes=ax[:-1], norm=_norm(norm))
        n_last = s[-1] if s is not None else None
        return jnp.fft.hfft(out, n=n_last, axis=ax[-1], norm=_norm(norm))

    return apply_op(f, x, op_name="hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    def f(v):
        ax = list(axes) if axes is not None else list(range(v.ndim))
        last = ax[-1]
        n_last = s[-1] if s is not None else None
        # hermitian (real-input) axis first, then complex ifft on the rest
        out = jnp.fft.ihfft(v, n=n_last, axis=last, norm=_norm(norm))
        if len(ax) > 1:
            rest_s = list(s[:-1]) if s is not None else None
            out = jnp.fft.ifftn(out, s=rest_s, axes=ax[:-1], norm=_norm(norm))
        return out

    return apply_op(f, x, op_name="ihfftn")


__all__ += ["hfft2", "ihfft2", "hfftn", "ihfftn"]
