"""Static-graph AMP (reference: python/paddle/static/amp — decorator.py
``decorate``, fp16_lists ``CustomOpLists``/``AutoMixedPrecisionLists``,
fp16_utils ``fp16_guard``/``cast_model_to_fp16``/``cast_parameters_to_fp16``).

TPU-native: the reference rewrites the static program with cast ops; here
the same rewrite is the distributed AMP pass over the recorded-Program IR
(distributed/passes.AMPPass), and bf16 is the default low precision (the
TPU-native choice — fp16 on request). Loss scaling is unnecessary for
bf16 (same exponent range as fp32); the decorated optimizer keeps the
reference's scaler-shaped surface with scale 1.
"""
from __future__ import annotations

import contextlib
from typing import Iterable, Optional

import jax.numpy as jnp

__all__ = ["decorate", "CustomOpLists", "AutoMixedPrecisionLists",
           "fp16_guard", "bf16_guard", "cast_model_to_fp16",
           "cast_model_to_bf16", "cast_parameters_to_fp16",
           "cast_parameters_to_bf16"]


class AutoMixedPrecisionLists:
    """Op allow/deny lists (reference fp16_lists.AutoMixedPrecisionLists):
    white ops run in low precision, black ops stay fp32."""

    def __init__(self, custom_white_list: Optional[Iterable[str]] = None,
                 custom_black_list: Optional[Iterable[str]] = None,
                 custom_black_varnames=None, dtype: str = "float16"):
        from ..amp.amp_lists import black_list, white_list

        self.white_list = set(white_list(dtype)) | {
            str(n).lower() for n in (custom_white_list or ())}
        self.black_list = (set(black_list(dtype)) | {
            str(n).lower() for n in (custom_black_list or ())})
        self.white_list -= self.black_list
        self.dtype = dtype


CustomOpLists = AutoMixedPrecisionLists


_in_guard = [False]


@contextlib.contextmanager
def fp16_guard():
    """Marks a region whose ops are amp-eligible (reference
    fp16_utils.fp16_guard). Recording captures ops either way; the guard
    is kept for script parity and future selective casting."""
    _in_guard[0] = True
    try:
        yield
    finally:
        _in_guard[0] = False


bf16_guard = fp16_guard


def _cast_program(program, dtype: str, amp_lists=None):
    from ..distributed.passes import new_pass

    attrs = {"dtype": dtype}
    if amp_lists is not None:
        attrs["custom_white_list"] = sorted(amp_lists.white_list)
        attrs["custom_black_list"] = sorted(amp_lists.black_list)
    name = ("auto_parallel_fp16" if dtype in ("float16", "fp16")
            else "auto_parallel_amp")
    return new_pass(name, attrs).apply(program)


def cast_model_to_fp16(program, amp_lists=None, use_fp16_guard: bool = True,
                       dest_type=None):
    """Rewrite the program's white-list ops to fp16 compute (reference
    fp16_utils.cast_model_to_fp16); returns the transformed program."""
    return _cast_program(program, "float16", amp_lists)


def cast_model_to_bf16(program, amp_lists=None, use_bf16_guard: bool = True):
    return _cast_program(program, "bfloat16", amp_lists)


def cast_parameters_to_fp16(place=None, program=None, scope=None,
                            to_fp16_var_names=None):
    """Cast stored params to fp16 (reference fp16_utils) — on TPU this is
    a scope-value dtype change; master copies stay with the optimizer."""
    _cast_params(program, scope, jnp.float16, to_fp16_var_names)


def cast_parameters_to_bf16(place=None, program=None, scope=None,
                            to_bf16_var_names=None):
    _cast_params(program, scope, jnp.bfloat16, to_bf16_var_names)


def _cast_params(program, scope, dt, names):
    from .program import default_main_program, global_scope

    program = program or default_main_program()
    scope = scope or global_scope()
    targets = set(names) if names else None
    for name in program.param_vars:
        if targets is not None and name not in targets:
            continue
        v = scope.var(name)
        if v is not None and hasattr(v, "astype") and jnp.issubdtype(
                jnp.result_type(v), jnp.floating):
            scope.set(name, v.astype(dt))
        p = program.param_objs.get(name)
        if p is not None and jnp.issubdtype(
                jnp.result_type(p._value), jnp.floating):
            p._value = p._value.astype(dt)


class _DecoratedOptimizer:
    """Optimizer wrapper (reference decorator.OptimizerWithMixedPrecision):
    minimize() casts the program through the AMP pass first; the scaler
    surface is identity for bf16 (no loss scaling needed on TPU)."""

    def __init__(self, optimizer, amp_lists=None, level="O1",
                 dtype="bfloat16", init_loss_scaling=1.0, **kw):
        self._opt = optimizer
        self._amp_lists = amp_lists
        self._dtype = dtype
        self._level = level
        self.program = None   # the casted program minimize() produced

    def __getattr__(self, item):
        return getattr(self._opt, item)

    def get_loss_scaling(self):
        return 1.0

    def amp_init(self, place=None, scope=None, test_program=None,
                 use_fp16_test=False):
        # only PURE (O2) mode casts stored params; O1 keeps fp32 masters
        # (reference decorator amp_init semantics)
        if self._level == "O2" and self._dtype in ("float16", "fp16"):
            cast_parameters_to_fp16(place, scope=scope)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .program import default_main_program, program_guard, \
            static_state

        prog = default_main_program()
        casted = _cast_program(prog, self._dtype, self._amp_lists)
        # the reference rewrites the program IN PLACE; recorded programs
        # are immutable clones, so the casted program (a) becomes the
        # default main program for subsequent exe.run(None) calls and
        # (b) is exposed as .program / returned state for explicit use.
        # NOTE: call minimize OUTSIDE a program_guard, or run the
        # returned .program explicitly — a guard's __exit__ restores the
        # pre-cast program.
        with program_guard(casted, startup_program or
                           static_state.startup_program):
            out = self._opt.minimize(loss)
        static_state.main_program = casted
        self.program = casted
        return out


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8, use_dynamic_loss_scaling=None,
             use_amp_guard=None, level="O1", dtype="bfloat16",
             use_pure_fp16=False, use_fp16_guard=None, master_weight=None,
             use_promote=False):
    """reference static/amp/decorator.py decorate."""
    if use_pure_fp16:
        dtype = "float16"
        level = "O2"   # pure fp16 IS O2: amp_init casts stored params
    return _DecoratedOptimizer(optimizer, amp_lists, level, dtype,
                               init_loss_scaling)
