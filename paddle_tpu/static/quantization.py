"""Program-level quantization passes (reference:
python/paddle/static/quantization/quantization_pass.py —
QuantizationTransformPass inserts fake_quantize/dequantize ops in front of
quantizable ops; QuantizationFreezePass rewrites them to fixed scales).

TPU-native: the Program here is the recorded-op IR (static/program.py), so
a "pass" is a node-list rewrite — insert absmax fake-quant nodes on the
inputs of matmul-class ops (QAT: scales ride the forward dynamically with
a straight-through estimator, so append_backward/minimize train through
them), then freeze weight scales to constants computed from the calibrated
scope for inference. int8 simulation math reuses
``paddle_tpu.quantization.fake_quant`` (STE custom_vjp).
"""
from __future__ import annotations

import itertools
from typing import Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp

from .program import Program, Scope, StaticNode, global_scope

__all__ = ["QuantizationTransformPass", "QuantizationFreezePass",
           "quant_aware", "convert"]

_QUANT_OP_TYPES = ("matmul", "mul", "conv2d", "linear")
_FQ_NAME = "fake_quantize_dequantize_absmax"
_vid_counter = itertools.count(1 << 62)


def _dyn_fake_quant(x, bits: int):
    """Absmax fake quant with runtime scale (QAT forward); STE backward
    comes from quantization._fake_quant's custom_vjp. Calls the RAW jnp
    core — the Tensor-level fake_quant routes through apply_op, which
    would re-enter record mode while the executor composes this node."""
    from ..quantization import _fake_quant

    scale = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
    scale = jnp.maximum(scale, jnp.asarray(1e-9, x.dtype))
    return _fake_quant(x, scale, float(2 ** (bits - 1) - 1))


def _fixed_fake_quant(x, scale: float, bits: int):
    from ..quantization import _fake_quant

    return _fake_quant(x, jnp.asarray(scale, jnp.result_type(x)),
                       float(2 ** (bits - 1) - 1))


class QuantizationTransformPass:
    """Insert fake-quant nodes on every float tensor input of quantizable
    ops (reference QuantizationTransformPass.apply: the
    _transform_forward insertion walk)."""

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 quantizable_op_type: Iterable[str] = _QUANT_OP_TYPES):
        self._wbits = int(weight_bits)
        self._abits = int(activation_bits)
        self._types = tuple(quantizable_op_type)

    def _quantizable(self, node: StaticNode) -> bool:
        # EXACT op-type match (reference matches op types, where 'mul' is
        # the legacy matmul op): substring matching would int8-quantize
        # elementwise 'multiply', 'bilinear', 'multi_*' etc.
        return (node.name or "").lower() in self._types

    def apply(self, program: Program) -> Program:
        out = program.clone()
        param_ids = set(program.param_vars.values())
        new_nodes = []
        n_inserted = 0
        quantized: Dict[Tuple[int, int], int] = {}  # (src vid, bits) → qvid
        for node in out.nodes:
            if not self._quantizable(node):
                new_nodes.append(node)
                continue
            new_slots = []
            for kind, v in node.in_ids:
                if kind != "var" or v not in out.var_meta:
                    new_slots.append((kind, v))
                    continue
                name, aval = out.var_meta[v]
                dt = getattr(aval, "dtype", None)
                if (dt is None or not jnp.issubdtype(dt, jnp.floating)
                        or len(getattr(aval, "shape", ())) < 1):
                    new_slots.append((kind, v))
                    continue
                bits = self._wbits if v in param_ids else self._abits
                qvid = quantized.get((v, bits))  # reuse across consumers
                if qvid is None:                 # (reference dequantized_vars)
                    qvid = next(_vid_counter)
                    quantized[(v, bits)] = qvid
                    out.add_var(qvid, f"{name}.quantized", aval)
                    new_nodes.append(StaticNode(
                        fn=lambda x, _b=bits: _dyn_fake_quant(x, _b),
                        in_ids=[("var", v)], const_args=None,
                        out_ids=[qvid], name=_FQ_NAME))
                    n_inserted += 1
                new_slots.append(("var", qvid))
            new_nodes.append(StaticNode(
                fn=node.fn, in_ids=new_slots, const_args=node.const_args,
                out_ids=node.out_ids, name=node.name))
        out.nodes = new_nodes
        out._quant_inserted = n_inserted
        out._quant_bits = (self._wbits, self._abits)
        return out


class QuantizationFreezePass:
    """Freeze WEIGHT fake-quants to fixed scales read from the (calibrated)
    scope (reference QuantizationFreezePass: scale transfer + op rewrite).
    Activation quants keep dynamic scales (the runtime absmax is the TPU-
    friendly form — no per-batch state to thread)."""

    def __init__(self, weight_bits: int = 8):
        self._wbits = int(weight_bits)

    def apply(self, program: Program,
              scope: Optional[Scope] = None) -> Program:
        scope = scope or global_scope()
        out = program.clone()
        id_to_pname = {vid: n for n, vid in program.param_vars.items()}
        scales: Dict[str, float] = {}
        new_nodes = []
        for node in out.nodes:
            src = node.in_ids[0][1] if node.in_ids else None
            if (node.name == _FQ_NAME and src in id_to_pname):
                pname = id_to_pname[src]
                val = scope.var(pname)
                if val is None and pname in out.param_objs:
                    val = out.param_objs[pname]._value
                scale = max(float(jnp.max(jnp.abs(jnp.asarray(val)))),
                            1e-9)  # zero-init params (bias) divide by scale
                scales[pname] = scale
                new_nodes.append(StaticNode(
                    fn=lambda x, _s=scale, _b=self._wbits:
                        _fixed_fake_quant(x, _s, _b),
                    in_ids=node.in_ids, const_args=None,
                    out_ids=node.out_ids,
                    name="fake_quantize_dequantize_frozen"))
            else:
                new_nodes.append(node)
        out.nodes = new_nodes
        out._quant_scales = scales
        return out


def quant_aware(program: Program, weight_bits: int = 8,
                activation_bits: int = 8,
                quantizable_op_type: Iterable[str] = _QUANT_OP_TYPES
                ) -> Program:
    """One-call QAT program transform (reference paddleslim-style
    quant_aware over a static program)."""
    return QuantizationTransformPass(
        weight_bits, activation_bits, quantizable_op_type).apply(program)


def convert(program: Program, scope: Optional[Scope] = None,
            weight_bits: int = 8) -> Program:
    """Freeze the trained/calibrated quant program for inference."""
    return QuantizationFreezePass(weight_bits).apply(program, scope)
