"""paddle.static parity surface (reference: python/paddle/static/).

Static mode here is record-then-jit: ops recorded at the apply_op choke
point (record.py), composed and compiled by Executor (executor.py). See
program.py for the design note.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor
from .executor import Executor
from .program import (Program, Scope, default_main_program,
                      default_startup_program, disable_static, enable_static,
                      global_scope, in_static_mode, program_guard,
                      static_state)
from .record import make_symbolic
from . import quantization  # noqa: F401  (reference static/quantization)
from . import amp  # noqa: F401  (reference static/amp)

__all__ = ["data", "Executor", "Program", "program_guard",
           "default_main_program", "default_startup_program", "scope_guard",
           "global_scope", "enable_static", "disable_static",
           "in_static_mode", "append_backward", "gradients", "InputSpec",
           "name_scope", "save", "load", "save_inference_model",
           "load_inference_model", "cpu_places", "cuda_places", "nn"]


class InputSpec:
    """reference paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


def data(name: str, shape: Sequence[int], dtype="float32",
         lod_level: int = 0) -> Tensor:
    """Declare a feed placeholder (reference paddle.static.data). Dynamic
    dims (None/-1) compile as size 1 unless the first feed fixes them — XLA
    needs static shapes, so the executor re-jits per concrete feed shape."""
    prog = default_main_program()
    dt = dtypes.convert_dtype(dtype)
    aval_shape = tuple(1 if (s is None or s == -1) else int(s) for s in shape)
    t = make_symbolic(jax.ShapeDtypeStruct(aval_shape, dt), name=name,
                      stop_gradient=True)
    prog.feed_vars[name] = id(t)
    prog.add_var(id(t), name, t._value)
    return t


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """reference fluid/backward.py:1885 — returns [(param, grad_var)];
    grad values materialize when the grad var is fetched (computed via
    jax.grad in the composed step)."""
    prog = default_main_program()
    if not hasattr(prog, "grad_vars"):
        prog.grad_vars = {}
    prog.loss_id = id(loss)  # the scalar the executor differentiates
    out = []
    params = parameter_list or list(prog.param_objs.values())
    for p in params:
        name = getattr(p, "name", None)
        if name is None or name not in prog.param_vars:
            continue
        aval = jax.ShapeDtypeStruct(tuple(int(s) for s in p.shape), p.dtype)
        g = make_symbolic(aval, name=f"{name}@GRAD")
        prog.add_var(id(g), g.name, aval)
        prog.grad_vars[id(g)] = name
        out.append((p, g))
    return out


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference paddle.static.gradients: symbolic grads of (summed)
    targets wrt feed inputs. Each returned var is fetchable; the executor
    computes it with ``jax.grad`` of the recorded program wrt the feeds
    (the reference appends grad ops into the ProgramDesc instead)."""
    prog = default_main_program()
    targets = list(targets) if isinstance(targets, (list, tuple)) else [targets]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    ng = set(id(v) for v in (no_grad_set or []))
    target_ids = tuple(id(t) for t in targets)
    vid_to_feed = {vid: name for name, vid in prog.feed_vars.items()}
    out = []
    for inp in inputs:
        if id(inp) in ng:
            out.append(None)
            continue
        feed_name = vid_to_feed.get(id(inp))
        if feed_name is None:
            raise ValueError(
                "static.gradients supports gradients wrt feed (data()) "
                "variables; for parameter gradients use append_backward")
        aval = inp._value if hasattr(inp, "_value") else inp
        g = make_symbolic(aval, name=f"{feed_name}@GRAD")
        prog.add_var(id(g), g.name, aval)
        if not hasattr(prog, "input_grad_vars"):
            prog.input_grad_vars = {}
        prog.input_grad_vars[id(g)] = (target_ids, feed_name)
        out.append(g)
    return out


class scope_guard:
    """Route Executor state through `scope` for the duration of the block
    (reference paddle.static.scope_guard)."""

    def __init__(self, scope: Scope):
        self.scope = scope

    def __enter__(self):
        from .program import _push_scope

        _push_scope(self.scope)
        return self

    def __exit__(self, *exc):
        from .program import _pop_scope

        _pop_scope()
        return False


def name_scope(prefix: str):
    import contextlib

    @contextlib.contextmanager
    def _ns():
        yield

    return _ns()


def save(program: Program, model_path: str, protocol: int = 4):
    """Persist program params (reference paddle.static.save → .pdparams)."""
    from ..framework import io as fio

    sd = {name: Tensor(global_scope().var(name)
                       if global_scope().var(name) is not None else p._value)
          for name, p in program.param_objs.items()
          if not name.startswith("__const_")}
    fio.save(sd, model_path + ".pdparams")


def load(program: Program, model_path: str, executor=None, var_list=None):
    from ..framework import io as fio

    sd = fio.load(model_path + ".pdparams")
    for name, val in sd.items():
        if name in program.param_objs:
            v = val._value if isinstance(val, Tensor) else val
            global_scope().set(name, v)
            program.param_objs[name]._value = v


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None, **kw):
    program = program or default_main_program()
    save(program, path_prefix)


def load_inference_model(path_prefix, executor, **kw):
    """Load a serving artifact saved by ``jit.save``/``save_inference_model``.

    Returns ``(program, feed_names, fetch_names)`` shaped like the reference
    API. ``program`` is an AOT-compiled Predictor
    (inference/api/analysis_predictor.h:148 Run analog): run it with
    ``program.run([input_arrays])`` (returns numpy outputs) — it is a
    compiled executable, not an op-list for ``Executor.run``.
    """
    import os as _os

    from ..inference import Config, create_predictor

    if _os.path.exists(path_prefix + ".stablehlo") or _os.path.exists(
            path_prefix + ".pdiparams"):
        pred = create_predictor(Config(path_prefix))
        exported = getattr(pred, "_exported", None)
        n_out = len(exported.out_avals) if exported is not None else 1
        return pred, pred.get_input_names(), [f"out{i}" for i in range(n_out)]
    raise FileNotFoundError(f"no inference artifact at {path_prefix}")


def cpu_places(device_count=None):
    import jax as _j

    return list(range(device_count or len(_j.devices("cpu"))))


def cuda_places(device_ids=None):
    return []


class _StaticNN:
    """paddle.static.nn facade — layers over the record mechanism."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
           activation=None, name=None):
        from ..nn.layer.common import Linear

        in_dim = int(np.prod(x.shape[num_flatten_dims:]))
        layer = Linear(in_dim, size)
        out = layer(x)
        if activation == "relu":
            from ..nn import functional as F

            out = F.relu(out)
        elif activation == "tanh":
            import paddle_tpu as _p

            out = _p.tanh(out)
        return out

    @staticmethod
    def batch_norm(input, **kw):
        from ..nn.layer.norm import BatchNorm1D

        return BatchNorm1D(int(input.shape[-1]))(input)


nn = _StaticNN()


# -- remaining reference static surface ------------------------------------
# (python/paddle/static/__init__.py __all__ parity)


Variable = Tensor  # static Program "Variable" ≙ the traced Tensor facade


class BuildStrategy:
    """Accepted-and-recorded build options (reference BuildStrategy —
    pass-manager knobs for the fused executor; XLA owns those passes
    here, so the knobs are inert but printable/settable)."""

    def __init__(self):
        self.__dict__["_opts"] = {}

    def __setattr__(self, k, v):
        self._opts[k] = v

    def __getattr__(self, k):
        try:
            return self.__dict__["_opts"][k]
        except KeyError:
            return None

    def __repr__(self):
        return f"BuildStrategy({self._opts})"


class ExecutionStrategy(BuildStrategy):
    """reference ExecutionStrategy — same inert-knob treatment."""


class CompiledProgram:
    """reference CompiledProgram(program) — the with_data_parallel /
    build-strategy wrapper. Compilation here happens in Executor.run via
    jax.jit; this wrapper carries the program + strategies through the
    same call sites."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, item):
        return getattr(self._program, item)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """reference static.create_global_var — a filled persistable var."""
    import jax.numpy as jnp

    t = Tensor(jnp.full(tuple(shape), value,
                        dtypes.convert_dtype(dtype)
                        if hasattr(dtypes, "convert_dtype") else dtype))
    t.persistable = persistable
    if name:
        t.name = name
    prog = default_main_program()
    if hasattr(prog, "param_objs") and name:
        scope = global_scope()
        scope.set(name, t._value)
    return t


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """reference static.create_parameter."""
    from .. import create_parameter as _top

    return _top(shape, dtype, name=name, attr=attr, is_bias=is_bias,
                default_initializer=default_initializer)


def device_guard(device=None):
    """reference static.device_guard — op placement hint. XLA owns
    placement; the context manager is accepted and inert."""
    import contextlib

    return contextlib.nullcontext()


def ipu_shard_guard(index=-1, stage=-1):
    import contextlib

    return contextlib.nullcontext()


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """reference static.nn.Print — identity op that prints at execution.
    jax.debug.print is the traced-print mechanism."""
    import jax

    v = input.value if isinstance(input, Tensor) else input
    jax.debug.print((message or "") + " {x}", x=v)
    return input


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference static.py_func — host-python op inside the graph via
    jax.pure_callback; ``backward_func(*inputs, *output_grads) -> input
    grads`` runs through a custom_vjp so the op is trainable."""
    import functools

    import jax
    import jax.numpy as jnp

    from ..core.autograd import apply_op

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = tuple(jax.ShapeDtypeStruct(tuple(o.shape), o.value.dtype
                                        if isinstance(o, Tensor) else o.dtype)
                   for o in outs)

    def host_fwd(*args):
        res = func(*args)
        res = res if isinstance(res, (list, tuple)) else (res,)
        return tuple(np.asarray(r) for r in res)

    def fwd_impl(*vals):
        result = jax.pure_callback(host_fwd, shapes, *vals)
        return result if len(shapes) > 1 else result[0]

    if backward_func is not None:
        in_shapes = None

        @jax.custom_vjp
        def op(*vals):
            return fwd_impl(*vals)

        def op_fwd(*vals):
            return fwd_impl(*vals), vals

        def op_bwd(res, g):
            gs = g if isinstance(g, (list, tuple)) else (g,)
            bshapes = tuple(jax.ShapeDtypeStruct(v.shape, v.dtype)
                            for v in res)

            def host_bwd(*args):
                grads = backward_func(*args)
                grads = grads if isinstance(grads, (list, tuple)) \
                    else (grads,)
                return tuple(np.asarray(r) for r in grads)

            return jax.pure_callback(host_bwd, bshapes, *res, *gs)

        op.defvjp(op_fwd, op_bwd)
        impl = op
    else:
        impl = fwd_impl

    result = apply_op(impl, *xs, op_name="py_func")
    if isinstance(out, (list, tuple)):
        return list(result) if isinstance(result, (list, tuple)) \
            else [result]
    return result[0] if isinstance(result, (list, tuple)) else result


# -- program/persistable (de)serialization ---------------------------------


def serialize_program(feed_vars, fetch_vars, program=None) -> bytes:
    """reference static.serialize_program — the portable program bytes.
    The XLA-native program format is the jit.save StableHLO artifact;
    here the Program's recorded graph is pickled (same role: re-runnable
    topology without weights)."""
    import pickle

    prog = program or default_main_program()
    return pickle.dumps({"nodes": len(prog.nodes),
                         "desc": prog.describe()
                         if hasattr(prog, "describe") else None})


def deserialize_program(data: bytes):
    import pickle

    return pickle.loads(data)


def serialize_persistables(feed_vars, fetch_vars, program=None) -> bytes:
    import pickle

    prog = program or default_main_program()
    return pickle.dumps({k: np.asarray(p._value)
                         for k, p in prog.param_objs.items()})


def deserialize_persistables(program, data: bytes, executor=None):
    import pickle

    state = pickle.loads(data)
    scope = global_scope()
    for k, v in state.items():
        if k in program.param_objs:
            import jax.numpy as jnp

            program.param_objs[k].set_value(jnp.asarray(v))
            scope.set(k, program.param_objs[k]._value)
    return state


def save_to_file(path: str, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars):
    """reference static.normalize_program — prune to the feed→fetch
    subgraph. The recorded Program already contains exactly the traced
    subgraph, so this is the identity with validation."""
    if program is None:
        raise TypeError("program must be a Program")
    return program


def save_program_state(program=None):
    prog = program or default_main_program()
    return {k: np.asarray(p._value) for k, p in prog.param_objs.items()}


def load_program_state(model_path, var_list=None):
    """reference static.load_program_state — state dict from a save()
    artifact."""
    from ..framework.io import load as fload

    state = fload(model_path if model_path.endswith(".pdparams")
                  else model_path + ".pdparams")
    return {k: np.asarray(v.value if hasattr(v, "value") else v)
            for k, v in state.items()}


def set_program_state(program, state_dict):
    """reference static.set_program_state."""
    import jax.numpy as jnp

    scope = global_scope()
    for k, v in state_dict.items():
        if k in program.param_objs:
            program.param_objs[k].set_value(jnp.asarray(v))
            scope.set(k, program.param_objs[k]._value)


# -- legacy metrics + EMA ---------------------------------------------------


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """reference static.accuracy — top-k accuracy over logits."""
    import jax.numpy as jnp

    from ..core.autograd import apply_op

    def f(lg, lb):
        topk = jnp.argsort(lg, axis=-1)[..., -k:]
        lb2 = lb.reshape(-1, 1)
        hit = jnp.any(topk == lb2, axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return apply_op(f, input, label, op_name="accuracy")


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """reference static.auc — ROC-AUC via thresholded TP/FP counts (the
    phi auc kernel's binning algorithm)."""
    import jax.numpy as jnp

    from ..core.autograd import apply_op

    def f(pred, lb):
        pos_score = pred[:, 1] if pred.ndim == 2 else pred
        lbf = lb.reshape(-1).astype(jnp.float32)
        thresholds = jnp.linspace(0.0, 1.0, num_thresholds)
        tp = jnp.sum((pos_score[None, :] > thresholds[:, None])
                     * lbf[None, :], axis=1)
        fp = jnp.sum((pos_score[None, :] > thresholds[:, None])
                     * (1 - lbf[None, :]), axis=1)
        tpr = tp / jnp.maximum(jnp.sum(lbf), 1e-6)
        fpr = fp / jnp.maximum(jnp.sum(1 - lbf), 1e-6)
        return -jnp.trapezoid(tpr, fpr)

    out = apply_op(f, input, label, op_name="auc")
    return out, [], []


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """reference static.ctr_metric_bundle — (auc, sqrerr, abserr, prob,
    q, pos, total) for CTR models."""
    import jax.numpy as jnp

    from ..core.autograd import apply_op

    a, _, _ = auc(input, label)

    def stats(pred, lb):
        pos_score = pred[:, 1] if pred.ndim == 2 else pred
        lbf = lb.reshape(-1).astype(jnp.float32)
        sqrerr = jnp.sum((pos_score - lbf) ** 2)
        abserr = jnp.sum(jnp.abs(pos_score - lbf))
        prob = jnp.sum(pos_score)
        pos = jnp.sum(lbf)
        total = jnp.asarray(lbf.shape[0], jnp.float32)
        return sqrerr, abserr, prob, pos, total

    out = apply_op(stats, input, label, op_name="ctr_metric_bundle")
    return (a,) + tuple(out)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """Legacy lr helper (reference static exponential_decay:
    lr * decay_rate^(step/decay_steps), floored per-interval when
    staircase)."""
    from ..optimizer.lr import LambdaDecay

    def factor(step):
        exp = step / float(decay_steps)
        if staircase:
            exp = float(int(exp))
        return decay_rate ** exp

    return LambdaDecay(learning_rate=learning_rate, lr_lambda=factor)


class WeightNormParamAttr:
    """reference static.WeightNormParamAttr — weight-norm
    reparameterization attr. Carried for API shape; the nn.utils
    weight_norm wrapper is the dygraph-path implementation."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer


class ExponentialMovingAverage:
    """EMA of parameters (reference static.ExponentialMovingAverage):
    update() folds current params into shadows, apply()/restore() swap.

    apply() targets the PARAMETER OBJECTS seen by update() (dygraph-EMA
    semantics) — a separately rebuilt program with same-named parameters
    is a different set of objects and is not touched."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}
        self._step = 0
        self._tracked = {}  # key -> the Parameter object itself

    def update(self, parameters=None):
        params = parameters or [
            p for _, p in default_main_program().param_objs.items()]
        self._step += 1
        import jax.numpy as jnp

        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in params:
            key = getattr(p, "name", None) or id(p)
            self._tracked[key] = p  # remember WHICH params we average
            prev = self._shadow.get(key)
            v = p.value.astype(jnp.float32)
            self._shadow[key] = v if prev is None else (
                d * prev + (1 - d) * v)

    def apply(self, executor=None, need_restore=True):
        import contextlib

        self._backup = {k: p.value for k, p in self._tracked.items()}
        for key, p in self._tracked.items():
            if key in self._shadow:
                p.set_value(self._shadow[key].astype(p.value.dtype))

        ema = self

        @contextlib.contextmanager
        def guard():
            try:
                yield
            finally:
                if need_restore:
                    ema.restore(executor)

        return guard()

    def restore(self, executor=None):
        for key, p in self._tracked.items():
            if key in self._backup:
                p.set_value(self._backup[key])
        self._backup = {}


def xpu_places(device_ids=None):
    raise RuntimeError("XPU is not available in a TPU-native build")


__all__ += ["Variable", "BuildStrategy", "ExecutionStrategy",
            "CompiledProgram", "create_global_var", "create_parameter",
            "device_guard", "ipu_shard_guard", "Print", "py_func",
            "serialize_program", "deserialize_program",
            "serialize_persistables", "deserialize_persistables",
            "save_to_file", "load_from_file", "normalize_program",
            "save_program_state", "load_program_state", "set_program_state",
            "accuracy", "auc", "ctr_metric_bundle", "exponential_decay",
            "WeightNormParamAttr", "ExponentialMovingAverage", "xpu_places"]
