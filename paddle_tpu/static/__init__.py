"""paddle.static parity surface (reference: python/paddle/static/).

Static mode here is record-then-jit: ops recorded at the apply_op choke
point (record.py), composed and compiled by Executor (executor.py). See
program.py for the design note.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor
from .executor import Executor
from .program import (Program, Scope, default_main_program,
                      default_startup_program, disable_static, enable_static,
                      global_scope, in_static_mode, program_guard,
                      static_state)
from .record import make_symbolic

__all__ = ["data", "Executor", "Program", "program_guard",
           "default_main_program", "default_startup_program", "scope_guard",
           "global_scope", "enable_static", "disable_static",
           "in_static_mode", "append_backward", "gradients", "InputSpec",
           "name_scope", "save", "load", "save_inference_model",
           "load_inference_model", "cpu_places", "cuda_places", "nn"]


class InputSpec:
    """reference paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


def data(name: str, shape: Sequence[int], dtype="float32",
         lod_level: int = 0) -> Tensor:
    """Declare a feed placeholder (reference paddle.static.data). Dynamic
    dims (None/-1) compile as size 1 unless the first feed fixes them — XLA
    needs static shapes, so the executor re-jits per concrete feed shape."""
    prog = default_main_program()
    dt = dtypes.convert_dtype(dtype)
    aval_shape = tuple(1 if (s is None or s == -1) else int(s) for s in shape)
    t = make_symbolic(jax.ShapeDtypeStruct(aval_shape, dt), name=name,
                      stop_gradient=True)
    prog.feed_vars[name] = id(t)
    prog.add_var(id(t), name, t._value)
    return t


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """reference fluid/backward.py:1885 — returns [(param, grad_var)];
    grad values materialize when the grad var is fetched (computed via
    jax.grad in the composed step)."""
    prog = default_main_program()
    if not hasattr(prog, "grad_vars"):
        prog.grad_vars = {}
    prog.loss_id = id(loss)  # the scalar the executor differentiates
    out = []
    params = parameter_list or list(prog.param_objs.values())
    for p in params:
        name = getattr(p, "name", None)
        if name is None or name not in prog.param_vars:
            continue
        aval = jax.ShapeDtypeStruct(tuple(int(s) for s in p.shape), p.dtype)
        g = make_symbolic(aval, name=f"{name}@GRAD")
        prog.add_var(id(g), g.name, aval)
        prog.grad_vars[id(g)] = name
        out.append((p, g))
    return out


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference paddle.static.gradients: symbolic grads of (summed)
    targets wrt feed inputs. Each returned var is fetchable; the executor
    computes it with ``jax.grad`` of the recorded program wrt the feeds
    (the reference appends grad ops into the ProgramDesc instead)."""
    prog = default_main_program()
    targets = list(targets) if isinstance(targets, (list, tuple)) else [targets]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    ng = set(id(v) for v in (no_grad_set or []))
    target_ids = tuple(id(t) for t in targets)
    vid_to_feed = {vid: name for name, vid in prog.feed_vars.items()}
    out = []
    for inp in inputs:
        if id(inp) in ng:
            out.append(None)
            continue
        feed_name = vid_to_feed.get(id(inp))
        if feed_name is None:
            raise ValueError(
                "static.gradients supports gradients wrt feed (data()) "
                "variables; for parameter gradients use append_backward")
        aval = inp._value if hasattr(inp, "_value") else inp
        g = make_symbolic(aval, name=f"{feed_name}@GRAD")
        prog.add_var(id(g), g.name, aval)
        if not hasattr(prog, "input_grad_vars"):
            prog.input_grad_vars = {}
        prog.input_grad_vars[id(g)] = (target_ids, feed_name)
        out.append(g)
    return out


class scope_guard:
    """Route Executor state through `scope` for the duration of the block
    (reference paddle.static.scope_guard)."""

    def __init__(self, scope: Scope):
        self.scope = scope

    def __enter__(self):
        from .program import _push_scope

        _push_scope(self.scope)
        return self

    def __exit__(self, *exc):
        from .program import _pop_scope

        _pop_scope()
        return False


def name_scope(prefix: str):
    import contextlib

    @contextlib.contextmanager
    def _ns():
        yield

    return _ns()


def save(program: Program, model_path: str, protocol: int = 4):
    """Persist program params (reference paddle.static.save → .pdparams)."""
    from ..framework import io as fio

    sd = {name: Tensor(global_scope().var(name)
                       if global_scope().var(name) is not None else p._value)
          for name, p in program.param_objs.items()
          if not name.startswith("__const_")}
    fio.save(sd, model_path + ".pdparams")


def load(program: Program, model_path: str, executor=None, var_list=None):
    from ..framework import io as fio

    sd = fio.load(model_path + ".pdparams")
    for name, val in sd.items():
        if name in program.param_objs:
            v = val._value if isinstance(val, Tensor) else val
            global_scope().set(name, v)
            program.param_objs[name]._value = v


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None, **kw):
    program = program or default_main_program()
    save(program, path_prefix)


def load_inference_model(path_prefix, executor, **kw):
    """Load a serving artifact saved by ``jit.save``/``save_inference_model``.

    Returns ``(program, feed_names, fetch_names)`` shaped like the reference
    API. ``program`` is an AOT-compiled Predictor
    (inference/api/analysis_predictor.h:148 Run analog): run it with
    ``program.run([input_arrays])`` (returns numpy outputs) — it is a
    compiled executable, not an op-list for ``Executor.run``.
    """
    import os as _os

    from ..inference import Config, create_predictor

    if _os.path.exists(path_prefix + ".stablehlo") or _os.path.exists(
            path_prefix + ".pdiparams"):
        pred = create_predictor(Config(path_prefix))
        exported = getattr(pred, "_exported", None)
        n_out = len(exported.out_avals) if exported is not None else 1
        return pred, pred.get_input_names(), [f"out{i}" for i in range(n_out)]
    raise FileNotFoundError(f"no inference artifact at {path_prefix}")


def cpu_places(device_count=None):
    import jax as _j

    return list(range(device_count or len(_j.devices("cpu"))))


def cuda_places(device_ids=None):
    return []


class _StaticNN:
    """paddle.static.nn facade — layers over the record mechanism."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
           activation=None, name=None):
        from ..nn.layer.common import Linear

        in_dim = int(np.prod(x.shape[num_flatten_dims:]))
        layer = Linear(in_dim, size)
        out = layer(x)
        if activation == "relu":
            from ..nn import functional as F

            out = F.relu(out)
        elif activation == "tanh":
            import paddle_tpu as _p

            out = _p.tanh(out)
        return out

    @staticmethod
    def batch_norm(input, **kw):
        from ..nn.layer.norm import BatchNorm1D

        return BatchNorm1D(int(input.shape[-1]))(input)


nn = _StaticNN()
