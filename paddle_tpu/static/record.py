"""Record-mode op capture — the static-graph analog of OpDesc appending.

When ``enable_static()`` is on, every op that reaches the apply_op choke
point lands here instead of executing: output avals come from
``jax.eval_shape`` (the InferShape/InferMeta analog, phi/infermeta/), and a
StaticNode is appended to the default main Program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.tree_util import tree_flatten, tree_unflatten

from ..core.tensor import Tensor
from .program import StaticNode, default_main_program

__all__ = ["record_op", "make_symbolic", "is_symbolic"]


def is_symbolic(t) -> bool:
    return isinstance(t, Tensor) and isinstance(
        t._value, jax.ShapeDtypeStruct)


def make_symbolic(aval: jax.ShapeDtypeStruct, name=None,
                  stop_gradient=True) -> Tensor:
    t = Tensor(aval, stop_gradient=stop_gradient, name=name)
    return t


def _aval_of(value):
    if isinstance(value, jax.ShapeDtypeStruct):
        return value
    return jax.ShapeDtypeStruct(jnp.shape(value), jnp.result_type(value))


def record_op(fn, args, kwargs, op_name):
    from ..nn.parameter import Parameter

    prog = default_main_program()
    leaves, treedef = tree_flatten((args, kwargs),
                                   is_leaf=lambda x: isinstance(x, Tensor))
    in_slots = []       # ("var", vid) | ("const", value)
    in_avals = []
    for l in leaves:
        if isinstance(l, Parameter):
            vid = prog.register_param(l)
            in_slots.append(("var", vid))
            in_avals.append(_aval_of(l._value))
        elif isinstance(l, Tensor):
            vid = id(l)
            if vid not in prog.var_meta:
                # concrete non-param tensor first seen: captured constant,
                # but register so later writes could address it
                prog.add_var(vid, l.name or f"tmp_{vid}", _aval_of(l._value))
                if not isinstance(l._value, jax.ShapeDtypeStruct):
                    prog.param_objs.setdefault(f"__const_{vid}", l)
            in_slots.append(("var", vid) if isinstance(
                l._value, jax.ShapeDtypeStruct) else ("const", l._value))
            in_avals.append(_aval_of(l._value))
        else:
            in_slots.append(("const", l))
            in_avals.append(l)

    def abstract(*avals):
        buf = list(avals)
        a, k = tree_unflatten(treedef, buf)
        return fn(*a, **k)

    out_avals = jax.eval_shape(abstract, *in_avals)
    out_leaves, out_treedef = tree_flatten(out_avals)
    outs = []
    out_ids = []
    for i, av in enumerate(out_leaves):
        t = make_symbolic(av, name=f"{op_name or 'op'}_{len(prog.nodes)}_{i}")
        prog.add_var(id(t), t.name, av)
        out_ids.append(id(t))
        outs.append(t)

    prog.add_node(StaticNode(
        fn=lambda *flat, _treedef=treedef, _fn=fn: _fn(
            *tree_unflatten(_treedef, list(flat))[0],
            **tree_unflatten(_treedef, list(flat))[1]),
        in_ids=in_slots, const_args=None, out_ids=out_ids,
        name=op_name or getattr(fn, "__name__", "op")))
    return tree_unflatten(out_treedef, outs)
