"""Static graph core: Program / record-mode tracing.

Reference: python/paddle/fluid/framework.py (Program:5206, Block:3540,
Variable:1238) + ProgramDesc/StandaloneExecutor (SURVEY.md §3.5).

TPU-native redesign: a Program is NOT an op-desc protobuf — it is a recorded
op list captured at the apply_op choke point while ``enable_static()`` is
on. ``Executor.run`` composes the recorded ops into one pure function of
(feeds, state) and ``jax.jit``s it — compilation IS the executor
(BuildOpFuncList/StreamAnalyzer ≙ XLA). Parameters encountered during
recording become state vars updated in the scope across runs, which gives
static training (append_backward/minimize) the reference semantics.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Program", "StaticNode", "static_state", "in_static_mode",
           "default_main_program", "default_startup_program",
           "program_guard", "enable_static", "disable_static", "Scope",
           "global_scope"]


class Scope:
    """Name → concrete value store (reference framework/scope.h)."""

    def __init__(self):
        self.vars: Dict[str, Any] = {}

    def var(self, name):
        return self.vars.get(name)

    def set(self, name, value):
        self.vars[name] = value

    def find_var(self, name):
        return self.vars.get(name)


_SCOPE_STACK: List[Scope] = [Scope()]


def global_scope() -> Scope:
    """The ACTIVE scope (top of the scope_guard stack)."""
    return _SCOPE_STACK[-1]


def _push_scope(scope: Scope):
    _SCOPE_STACK.append(scope)


def _pop_scope():
    if len(_SCOPE_STACK) > 1:
        _SCOPE_STACK.pop()


class StaticNode:
    __slots__ = ("fn", "in_ids", "const_args", "out_ids", "name")

    def __init__(self, fn, in_ids, const_args, out_ids, name):
        self.fn = fn
        self.in_ids = in_ids        # var-id per tensor input position
        self.const_args = const_args  # flat raw leaves with None at tensor slots
        self.out_ids = out_ids
        self.name = name


class Program:
    """reference Program:5206 — records ops; run via Executor."""

    _counter = [0]

    def __init__(self):
        Program._counter[0] += 1
        self.id = Program._counter[0]
        self.nodes: List[StaticNode] = []
        self.var_meta: Dict[int, Tuple[str, Any]] = {}   # id → (name, aval)
        self.feed_vars: Dict[str, int] = {}              # data() name → id
        self.param_vars: Dict[str, int] = {}             # param name → id
        self.param_objs: Dict[str, Any] = {}
        self.train_config = None  # (optimizer, loss_var_id, grad_map)
        self._var_names: Dict[int, str] = {}
        self.random_seed = None

    # -- recording helpers (called from apply_op) ---------------------------
    def add_var(self, vid: int, name: str, aval):
        self.var_meta[vid] = (name, aval)

    def add_node(self, node: StaticNode):
        self.nodes.append(node)

    def register_param(self, param):
        name = param.name
        vid = id(param)
        if name not in self.param_vars:
            self.param_vars[name] = vid
            self.param_objs[name] = param
            self.add_var(vid, name, jax.ShapeDtypeStruct(
                tuple(int(s) for s in param.shape), param.dtype))
        return self.param_vars[name]

    def list_vars(self):
        return list(self.var_meta.values())

    def clone(self, for_test: bool = False) -> "Program":
        p = Program.__new__(Program)
        # FRESH id: the executor caches compiled steps by node identity —
        # a transformed clone (amp/recompute passes wrap fns in place,
        # keeping the node COUNT) must never alias the original's cache.
        # _origin_id keeps optimizer state continuous across clones (the
        # reference's clone shares scope variables the same way).
        Program._counter[0] += 1
        p.id = Program._counter[0]
        p._origin_id = getattr(self, "_origin_id", self.id)
        p.nodes = list(self.nodes)
        p.var_meta = dict(self.var_meta)
        p.feed_vars = dict(self.feed_vars)
        p.param_vars = dict(self.param_vars)
        p.param_objs = dict(self.param_objs)
        p.train_config = None if for_test else self.train_config
        p._var_names = dict(self._var_names)
        p.random_seed = self.random_seed
        # gradient-fetch bookkeeping must survive transforms: without it a
        # grad fetch on the clone would silently take the non-grad path
        for attr in ("grad_vars", "input_grad_vars", "loss_id"):
            if hasattr(self, attr):
                v = getattr(self, attr)
                setattr(p, attr, dict(v) if isinstance(v, dict) else v)
        return p

    def __repr__(self):
        return (f"Program(id={self.id}, ops={len(self.nodes)}, "
                f"feeds={list(self.feed_vars)}, params={list(self.param_vars)})")

    global_block = lambda self: _BlockView(self)


class _BlockView:
    """Minimal Block facade (reference Block:3540) over a Program."""

    def __init__(self, program):
        self.program = program

    @property
    def ops(self):
        return self.program.nodes

    def var(self, name):
        for vid, (n, aval) in self.program.var_meta.items():
            if n == name:
                return aval
        raise KeyError(name)


class _StaticState(threading.local):
    def __init__(self):
        self.enabled = False
        self.main_program: Optional[Program] = None
        self.startup_program: Optional[Program] = None


static_state = _StaticState()


def in_static_mode() -> bool:
    return static_state.enabled


def enable_static():
    static_state.enabled = True
    if static_state.main_program is None:
        static_state.main_program = Program()
        static_state.startup_program = Program()


def disable_static():
    static_state.enabled = False


def default_main_program() -> Program:
    if static_state.main_program is None:
        static_state.main_program = Program()
        static_state.startup_program = Program()
    return static_state.main_program


def default_startup_program() -> Program:
    if static_state.startup_program is None:
        static_state.startup_program = Program()
    return static_state.startup_program


class program_guard:
    """reference fluid/framework.py:7228."""

    def __init__(self, main_program: Program, startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program or Program()

    def __enter__(self):
        self._prev = (static_state.main_program, static_state.startup_program)
        static_state.main_program = self.main
        static_state.startup_program = self.startup
        return self

    def __exit__(self, *exc):
        static_state.main_program, static_state.startup_program = self._prev
        return False
