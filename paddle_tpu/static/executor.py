"""Static Executor — compose the recorded Program into ONE jitted function.

Reference: python/paddle/fluid/executor.py:895 (Executor, run:1277) →
StandaloneExecutor/ProgramInterpreter (SURVEY.md §3.5). Here composition +
``jax.jit`` replaces BuildOpFuncList + instruction scheduling: XLA performs
the dependency analysis, fusion, and stream assignment the interpreter
hand-rolls. The jitted step is cached per (program, feeds, fetch) signature;
training programs (minimize()) also return updated params/opt-state, which
the executor writes back to the scope — the state round-trip of
Scope/Variable."""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .program import Program, default_main_program, global_scope

__all__ = ["Executor"]


def _avals(tree):
    """Shape/dtype skeleton of a pytree — kept (instead of the live arrays)
    on the Program for CostModel.static_cost re-lowering, so no stale
    generation of params/opt state stays pinned in device memory."""
    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(jnp.shape(a), jnp.result_type(a)),
        tree)


def _walk(prog: Program, env: Dict[int, Any]):
    for node in prog.nodes:
        flat = []
        for kind, v in node.in_ids:
            flat.append(env[v] if kind == "var" else v)
        out = node.fn(*flat)
        leaves = jax.tree.leaves(out)
        for vid, val in zip(node.out_ids, leaves):
            env[vid] = val
    return env


class Executor:
    """reference executor.py:895."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[tuple, Any] = {}
        self._aval_cache: Dict[tuple, Any] = {}

    def close(self):
        self._cache.clear()
        self._aval_cache.clear()

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, scope=None, return_numpy: bool = True):
        prog = program if program is not None else default_main_program()
        scope = scope or global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []

        # startup program (or any program with no nodes): initialize scope
        # params from their eager initial values
        if not prog.nodes:
            for name, p in prog.param_objs.items():
                scope.set(name, p._value)
            return []

        # ensure params present in scope
        for name, p in prog.param_objs.items():
            if scope.var(name) is None:
                scope.set(name, p._value)

        fetch_ids = []
        for f in fetch_list:
            if isinstance(f, Tensor):
                fetch_ids.append(id(f))
            else:
                raise TypeError("fetch_list entries must be program outputs")

        feed_names = tuple(sorted(feed))
        # content-aware key: pure clones share node OBJECTS and hit the
        # cache; pass-transformed programs carry new StaticNodes and miss
        key = (getattr(prog, "_origin_id", prog.id),
               tuple(id(n) for n in prog.nodes), tuple(fetch_ids),
               feed_names, prog.train_config is not None)
        step = self._cache.get(key)
        if step is None:
            step = self._build(prog, fetch_ids, feed_names)
            self._cache[key] = step

        param_names = tuple(sorted(prog.param_vars))
        params = {n: scope.var(n) for n in param_names}
        feeds = {n: jnp.asarray(np.asarray(
            feed[n]._value if isinstance(feed[n], Tensor) else feed[n]))
            for n in feed_names}
        opt_key = f"__opt_state_{getattr(prog, '_origin_id', prog.id)}"
        opt_state = scope.var(opt_key)

        if prog.train_config is not None:
            lr = jnp.asarray(prog.train_config[0].get_lr(), jnp.float32)
            if key not in self._aval_cache:  # shapes invariant per step fn
                self._aval_cache[key] = _avals((feeds, params, opt_state,
                                                lr))
            prog._last_step_args = (step, self._aval_cache[key])
            fetches, new_params, opt_state = step(feeds, params, opt_state, lr)
            for n, v in new_params.items():
                scope.set(n, v)
                prog.param_objs[n]._value = v  # keep eager view in sync
            scope.set(opt_key, opt_state)
        else:
            if key not in self._aval_cache:
                self._aval_cache[key] = _avals((feeds, params))
            prog._last_step_args = (step, self._aval_cache[key])
            fetches = step(feeds, params)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    # -- composition --------------------------------------------------------
    def _build(self, prog: Program, fetch_ids, feed_names):
        param_names = tuple(sorted(prog.param_vars))
        grad_vars = getattr(prog, "grad_vars", {})  # vid → param name

        def base_env(feeds, params):
            env: Dict[int, Any] = {}
            for n in feed_names:
                env[prog.feed_vars[n]] = feeds[n]
            for n in param_names:
                env[prog.param_vars[n]] = params[n]
            return env

        def forward(feeds, params):
            return _walk(prog, base_env(feeds, params))

        input_grad_vars = getattr(prog, "input_grad_vars", {})

        def _input_grads_for(fid, feeds, params):
            target_ids, feed_name = input_grad_vars[fid]

            def f(fv):
                # differentiate ONLY the requested feed — grad over the whole
                # feeds dict would reject integer feeds (token ids)
                env = forward({**feeds, feed_name: fv}, params)
                return sum(jnp.sum(env[t]) for t in target_ids)

            return jax.grad(f)(feeds[feed_name])

        if prog.train_config is None and not any(
                fid in grad_vars for fid in fetch_ids):

            @jax.jit
            def infer_step(feeds, params):
                env = forward(feeds, params)
                return [env[fid] if fid not in input_grad_vars
                        else _input_grads_for(fid, feeds, params)
                        for fid in fetch_ids]

            return infer_step

        # training / gradient path
        tc = prog.train_config
        loss_id = tc[1] if tc else getattr(prog, "loss_id", None)
        if loss_id is None:
            raise ValueError(
                "gradient fetch requires append_backward(loss) to have "
                "marked the loss on this program")

        def loss_of(params, feeds):
            env = forward(feeds, params)
            l = env[loss_id]
            return jnp.sum(l), env

        if tc is not None:
            optimizer = tc[0]

            @jax.jit
            def train_step(feeds, params, opt_state, lr):
                # lr enters as a traced ARGUMENT so schedulers/set_lr take
                # effect without re-tracing the cached step
                (loss, env), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(params, feeds)
                new_params, opt_state = optimizer._static_update(
                    params, grads, opt_state, lr=lr)
                fetches = [
                    grads[grad_vars[fid]] if fid in grad_vars
                    else _input_grads_for(fid, feeds, params)
                    if fid in input_grad_vars else env.get(fid)
                    for fid in fetch_ids]
                return fetches, new_params, opt_state

            return train_step

        @jax.jit
        def grad_step(feeds, params):
            (loss, env), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params, feeds)
            return [
                grads[grad_vars[fid]] if fid in grad_vars
                else _input_grads_for(fid, feeds, params)
                if fid in input_grad_vars else env.get(fid)
                for fid in fetch_ids]

        return grad_step
