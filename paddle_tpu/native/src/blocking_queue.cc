// Bounded MPMC blocking queue of byte blobs.
//
// TPU-native analog of the reference's C++ data-pipeline queue
// (paddle/fluid/operators/reader/blocking_queue.h; the DataLoader's
// multiprocess workers feed shared-memory tensors into it,
// python/paddle/io/dataloader/dataloader_iter.py:358). The C ABI keeps
// Python binding at ctypes level — no pybind11 (not in this image).
//
// Semantics: push blocks when full, pop blocks when empty; close() wakes
// all waiters; pop on a closed empty queue returns -1.

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

namespace {

struct Blob {
  std::vector<uint8_t> data;
};

struct BlockingQueue {
  explicit BlockingQueue(size_t cap) : capacity(cap) {}
  size_t capacity;
  std::deque<Blob> items;
  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;
  bool closed = false;
  uint64_t pushed = 0;
  uint64_t popped = 0;
};

}  // namespace

extern "C" {

void* bq_create(uint64_t capacity) {
  return new BlockingQueue(capacity ? capacity : 1);
}

void bq_destroy(void* q) { delete static_cast<BlockingQueue*>(q); }

// 0 on success, -1 if closed.
int bq_push(void* qp, const uint8_t* data, uint64_t size) {
  auto* q = static_cast<BlockingQueue*>(qp);
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_full.wait(lk, [&] { return q->items.size() < q->capacity || q->closed; });
  if (q->closed) return -1;
  Blob b;
  b.data.assign(data, data + size);
  q->items.push_back(std::move(b));
  ++q->pushed;
  q->not_empty.notify_one();
  return 0;
}

// Returns blob size (>=0) with contents copied into out (caller sized it via
// bq_peek_size), or -1 if closed-and-drained.
int64_t bq_peek_size(void* qp) {
  auto* q = static_cast<BlockingQueue*>(qp);
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_empty.wait(lk, [&] { return !q->items.empty() || q->closed; });
  if (q->items.empty()) return -1;
  return static_cast<int64_t>(q->items.front().data.size());
}

int64_t bq_pop(void* qp, uint8_t* out, uint64_t out_cap) {
  auto* q = static_cast<BlockingQueue*>(qp);
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_empty.wait(lk, [&] { return !q->items.empty() || q->closed; });
  if (q->items.empty()) return -1;
  Blob b = std::move(q->items.front());
  q->items.pop_front();
  ++q->popped;
  q->not_full.notify_one();
  uint64_t n = b.data.size();
  if (n > out_cap) n = out_cap;
  std::memcpy(out, b.data.data(), n);
  return static_cast<int64_t>(b.data.size());
}

void bq_close(void* qp) {
  auto* q = static_cast<BlockingQueue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  q->closed = true;
  q->not_full.notify_all();
  q->not_empty.notify_all();
}

uint64_t bq_size(void* qp) {
  auto* q = static_cast<BlockingQueue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->items.size();
}

}  // extern "C"
