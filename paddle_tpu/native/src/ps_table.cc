// Parameter-server data plane: sparse/dense table shards served over TCP.
//
// TPU-native analog of the reference's brpc PS data plane
// (paddle/fluid/distributed/ps/service/brpc_ps_server.cc handlers over
// ps/table/memory_sparse_table.cc with server-side sparse optimizers,
// sparse_sgd_rule.cc). The Python plane (distributed/ps/__init__.py)
// carries the full feature set (entry-admission policies, show/click
// accessors); THIS plane is the native hot path for plain embedding
// tables — the HBM-exceeding lookup/update traffic brpc exists for.
//
// Wire protocol (little-endian), one request per message:
//   request:  u8 op | u32 nlen | name bytes | u64 n | payload
//     op: 0=CREATE 1=PULL 2=PUSH 3=DENSE_INIT 4=DENSE_PULL 5=DENSE_PUSH
//         6=BARRIER 7=SAVE 8=STATS 9=STOP
//         10=LIST (no payload; response payload = table names joined by
//            '\n', truncated client-side only by the caller's out_cap —
//            the server always sends the full list; native.py stats()
//            depends on this op)
//   response: i64 status | u64 plen | payload     (status<0 = error)
//     error statuses: -1 unknown op, -2 io, -3 no such table/entry,
//     -4 dim mismatch, -5 bad barrier world, -6 wire size over cap or
//     invalid name, -7 server-side exception (connection closes),
//     -9 barrier aborted by server stop.
//     -6 closes the connection ONLY when the request payload could not
//     be read under the cap (the unread bytes would desync the stream);
//     a -6 for an invalid CREATE name or an over-cap PULL response
//     leaves the fully-read connection open.
//
// Row init matches the Python plane EXACTLY (hash_uniform below ==
// distributed/ps/__init__.py::_hash_uniform), so a table built through
// either plane is bit-identical — cross-plane parity is tested.

#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <atomic>
#include <condition_variable>
#include <exception>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

bool read_n(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= r;
  }
  return true;
}

bool write_n(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= r;
  }
  return true;
}

// Wire-supplied sizes are UNTRUSTED: n/nlen/dim come off the socket, and
// an overflowing n*dim*4 under-allocates the payload buffer while the
// i<n loops still walk the full range (heap OOB), while a huge-but-valid
// n would bad_alloc inside a detached thread (std::terminate kills the
// whole host process — the server runs in-process of the Python trainer).
constexpr uint64_t kMaxReqBytes = 1ull << 31;  // 2 GiB per request
constexpr uint32_t kMaxNameLen = 4096;

// total = a*b + c with overflow + cap check. Callers pass small c.
inline bool checked_size(uint64_t a, uint64_t b, uint64_t c, uint64_t* total) {
  if (b != 0 && a > (kMaxReqBytes - c) / b) return false;
  *total = a * b + c;
  return *total <= kMaxReqBytes;
}

// Table names become save/load file path components — reject separators
// and traversal server-side (a raw client could otherwise escape the
// SAVE dirname; native.py also rejects these client-side).
bool valid_table_name(const std::string& name) {
  if (name.empty() || name.size() > 256) return false;
  if (name.find('/') != std::string::npos) return false;
  if (name.find("..") != std::string::npos) return false;
  if (name.find('\n') != std::string::npos) return false;
  return true;
}

// splitmix64 — the shared row-init hash (Python plane mirrors this).
inline uint64_t sm64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

struct TableCfg {
  uint32_t dim = 0;
  uint8_t opt = 0;        // 0=sgd 1=adagrad 2=adam
  uint8_t init_kind = 0;  // 0=uniform 1=zeros
  uint64_t seed = 0;      // full width — Python hashes the full seed too
  float lr = 0.01f, b1 = 0.9f, b2 = 0.999f, eps = 1e-8f, init_range = 0.1f;
};

struct Row {
  std::vector<float> w;
  std::vector<float> s0;  // adagrad acc / adam m
  std::vector<float> s1;  // adam v
  int64_t t = 0;          // adam step
};

struct Table {
  TableCfg cfg;
  std::unordered_map<int64_t, Row> rows;
  std::mutex mu;
};

struct Server {
  int listen_fd = -1;
  uint32_t server_idx = 0;
  std::thread thread;
  std::atomic<bool> stop{false};
  std::mutex tables_mu;
  std::map<std::string, Table*> tables;  // Table* stable across rehash
  std::mutex dense_mu;
  std::map<std::string, std::vector<float>> dense;
  std::mutex barrier_mu;
  std::condition_variable barrier_cv;
  std::map<std::string, int64_t> barrier_count;
  std::atomic<int> active_workers{0};  // detached serve_client threads
  std::mutex fds_mu;
  std::vector<int> client_fds;
};

void init_row(const TableCfg& cfg, uint32_t server_idx, int64_t rid,
              std::vector<float>* w) {
  w->resize(cfg.dim);
  if (cfg.init_kind == 1) {
    std::fill(w->begin(), w->end(), 0.0f);
    return;
  }
  uint64_t h0 = sm64(sm64(cfg.seed * 1000003ull + server_idx) ^
                     static_cast<uint64_t>(rid));
  for (uint32_t j = 0; j < cfg.dim; ++j) {
    double u = static_cast<double>(sm64(h0 + j) >> 11) *
               (1.0 / 9007199254740992.0);  // [0,1) from the top 53 bits
    (*w)[j] = static_cast<float>((2.0 * u - 1.0) * cfg.init_range);
  }
}

Table* get_table(Server* s, const std::string& name) {
  std::lock_guard<std::mutex> lk(s->tables_mu);
  auto it = s->tables.find(name);
  return it == s->tables.end() ? nullptr : it->second;
}

void apply_push(Table* t, uint32_t server_idx, int64_t rid, const float* g) {
  const TableCfg& c = t->cfg;
  auto it = t->rows.find(rid);
  if (it == t->rows.end()) {
    it = t->rows.emplace(rid, Row{}).first;
    init_row(c, server_idx, rid, &it->second.w);
  }
  Row& r = it->second;
  float* w = r.w.data();
  if (c.opt == 0) {  // sgd
    for (uint32_t j = 0; j < c.dim; ++j) w[j] -= c.lr * g[j];
  } else if (c.opt == 1) {  // adagrad
    if (r.s0.empty()) r.s0.assign(c.dim, 0.0f);
    for (uint32_t j = 0; j < c.dim; ++j) {
      r.s0[j] += g[j] * g[j];
      w[j] -= c.lr * g[j] / (std::sqrt(r.s0[j]) + c.eps);
    }
  } else {  // adam
    if (r.s0.empty()) {
      r.s0.assign(c.dim, 0.0f);
      r.s1.assign(c.dim, 0.0f);
    }
    r.t += 1;
    double bc1 = 1.0 - std::pow(static_cast<double>(c.b1), r.t);
    double bc2 = 1.0 - std::pow(static_cast<double>(c.b2), r.t);
    for (uint32_t j = 0; j < c.dim; ++j) {
      r.s0[j] = c.b1 * r.s0[j] + (1.0f - c.b1) * g[j];
      r.s1[j] = c.b2 * r.s1[j] + (1.0f - c.b2) * g[j] * g[j];
      float mh = static_cast<float>(r.s0[j] / bc1);
      float vh = static_cast<float>(r.s1[j] / bc2);
      w[j] -= c.lr * mh / (std::sqrt(vh) + c.eps);
    }
  }
}

int64_t do_save(Server* s, const std::string& dirname) {
  ::mkdir(dirname.c_str(), 0777);  // EEXIST is fine
  // snapshot the table list only — holding tables_mu across the file
  // I/O would stall every concurrent pull/push for the whole save
  std::vector<std::pair<std::string, Table*>> snapshot;
  {
    std::lock_guard<std::mutex> lk(s->tables_mu);
    snapshot.assign(s->tables.begin(), s->tables.end());
  }
  for (auto& kv : snapshot) {
    Table* t = kv.second;
    std::lock_guard<std::mutex> tl(t->mu);
    std::string path = dirname + "/" + kv.first + ".shard" +
                       std::to_string(s->server_idx) + ".psbin";
    FILE* f = std::fopen(path.c_str(), "wb");
    if (!f) return -2;
    uint32_t dim = t->cfg.dim;
    uint64_t n = t->rows.size();
    std::fwrite(&dim, 4, 1, f);
    std::fwrite(&n, 8, 1, f);
    for (auto& row : t->rows) {
      std::fwrite(&row.first, 8, 1, f);
      std::fwrite(row.second.w.data(), 4, dim, f);
    }
    std::fclose(f);
  }
  return 0;
}

void serve_client(Server* s, int fd) {
  std::vector<uint8_t> payload, out;
  for (;;) {
    uint8_t op;
    uint32_t nlen;
    uint64_t n;
    if (!read_n(fd, &op, 1) || !read_n(fd, &nlen, 4)) break;
    if (nlen > kMaxNameLen) break;  // protocol violation: close
    std::string name(nlen, '\0');
    if (nlen && !read_n(fd, name.data(), nlen)) break;
    if (!read_n(fd, &n, 8)) break;

    int64_t status = 0;
    // set when the request's payload could not be (fully) read off the
    // wire under the size cap — the stream is desynced, so the reply is
    // followed by a close instead of another parse round
    bool close_conn = false;
    out.clear();
    try {
    switch (op) {
      case 0: {  // CREATE: payload = packed TableCfg
        TableCfg cfg;
        payload.resize(sizeof(TableCfg));
        if (!read_n(fd, payload.data(), payload.size())) goto done;
        std::memcpy(&cfg, payload.data(), sizeof(TableCfg));
        if (!valid_table_name(name)) {
          status = -6;
          break;
        }
        std::lock_guard<std::mutex> lk(s->tables_mu);
        auto it = s->tables.find(name);
        if (it == s->tables.end()) {
          auto* t = new Table();
          t->cfg = cfg;
          s->tables[name] = t;
        } else {
          // rows may have been restored by pst_server_load under a
          // default config: adopt the caller's config, keep rows
          std::lock_guard<std::mutex> tl(it->second->mu);
          if (it->second->cfg.dim != cfg.dim) {
            status = -4;
          } else {
            it->second->cfg = cfg;
          }
        }
        break;
      }
      case 1: {  // PULL: n ids -> dim + n*dim floats
        uint64_t need = 0;
        if (!checked_size(n, 8, 0, &need)) {
          status = -6;
          close_conn = true;
          break;
        }
        payload.resize(need);
        if (n && !read_n(fd, payload.data(), payload.size())) goto done;
        Table* t = get_table(s, name);
        if (!t) {
          status = -3;
          break;
        }
        const int64_t* ids = reinterpret_cast<const int64_t*>(payload.data());
        // cfg is written by the CREATE adopt path under t->mu — dim must
        // be read under the same lock (UB otherwise)
        std::lock_guard<std::mutex> lk(t->mu);
        uint32_t dim = t->cfg.dim;
        uint64_t osz = 0;  // response size: payload was read, keep conn
        if (!checked_size(n, static_cast<uint64_t>(dim) * 4, 4, &osz)) {
          status = -6;
          break;
        }
        out.resize(osz);
        std::memcpy(out.data(), &dim, 4);
        float* dst = reinterpret_cast<float*>(out.data() + 4);
        for (uint64_t i = 0; i < n; ++i) {
          auto it = t->rows.find(ids[i]);
          if (it == t->rows.end()) {
            it = t->rows.emplace(ids[i], Row{}).first;
            init_row(t->cfg, s->server_idx, ids[i], &it->second.w);
          }
          std::memcpy(dst + i * dim, it->second.w.data(), dim * 4);
        }
        break;
      }
      case 2: {  // PUSH: u32 dim | n ids | n*dim grads
        uint32_t dim;
        if (!read_n(fd, &dim, 4)) goto done;
        uint64_t need = 0;
        if (!checked_size(n, 8ull + static_cast<uint64_t>(dim) * 4, 0,
                          &need)) {
          status = -6;
          close_conn = true;
          break;
        }
        payload.resize(need);
        if (n && !read_n(fd, payload.data(), payload.size())) goto done;
        Table* t = get_table(s, name);
        if (!t) {
          status = -3;
          break;
        }
        const int64_t* ids = reinterpret_cast<const int64_t*>(payload.data());
        const float* g = reinterpret_cast<const float*>(payload.data() + n * 8);
        std::lock_guard<std::mutex> lk(t->mu);  // cfg read + row updates
        if (dim != t->cfg.dim) {
          status = -4;
          break;
        }
        for (uint64_t i = 0; i < n; ++i)
          apply_push(t, s->server_idx, ids[i], g + i * dim);
        break;
      }
      case 3: {  // DENSE_INIT: n floats (first write wins, like setdefault)
        uint64_t need = 0;
        if (!checked_size(n, 4, 0, &need)) {
          status = -6;
          close_conn = true;
          break;
        }
        payload.resize(need);
        if (n && !read_n(fd, payload.data(), payload.size())) goto done;
        const float* v = reinterpret_cast<const float*>(payload.data());
        std::lock_guard<std::mutex> lk(s->dense_mu);
        if (!s->dense.count(name)) s->dense[name].assign(v, v + n);
        break;
      }
      case 4: {  // DENSE_PULL
        std::lock_guard<std::mutex> lk(s->dense_mu);
        auto it = s->dense.find(name);
        if (it == s->dense.end()) {
          status = -3;
          break;
        }
        out.resize(it->second.size() * 4);
        std::memcpy(out.data(), it->second.data(), out.size());
        break;
      }
      case 5: {  // DENSE_PUSH: f32 lr | n grads  (server-side sgd)
        float lr;
        if (!read_n(fd, &lr, 4)) goto done;
        uint64_t need = 0;
        if (!checked_size(n, 4, 0, &need)) {
          status = -6;
          close_conn = true;
          break;
        }
        payload.resize(need);
        if (n && !read_n(fd, payload.data(), payload.size())) goto done;
        const float* g = reinterpret_cast<const float*>(payload.data());
        std::lock_guard<std::mutex> lk(s->dense_mu);
        auto it = s->dense.find(name);
        if (it == s->dense.end() || it->second.size() != n) {
          status = -3;
          break;
        }
        for (uint64_t j = 0; j < n; ++j) it->second[j] -= lr * g[j];
        break;
      }
      case 6: {  // BARRIER: n = world; status = arrival position 1..world
        int64_t world = static_cast<int64_t>(n);
        if (world < 1) {  // div-by-zero would SIGFPE the whole server
          status = -5;
          break;
        }
        std::unique_lock<std::mutex> lk(s->barrier_mu);
        int64_t count = ++s->barrier_count[name];
        int64_t pos = (count - 1) % world + 1;
        int64_t target = ((count - 1) / world + 1) * world;
        s->barrier_cv.wait(lk, [&] {
          return s->barrier_count[name] >= target || s->stop.load();
        });
        s->barrier_cv.notify_all();
        // a stop-woken waiter whose barrier never filled must NOT look
        // like a completed barrier — callers would proceed as if every
        // peer had arrived
        status = s->barrier_count[name] >= target ? pos : -9;
        break;
      }
      case 7:  // SAVE: name = dirname
        status = do_save(s, name);
        break;
      case 8: {  // STATS: status = row count of table `name`
        Table* t = get_table(s, name);
        if (!t) {
          status = -3;
          break;
        }
        std::lock_guard<std::mutex> lk(t->mu);
        status = static_cast<int64_t>(t->rows.size());
        break;
      }
      case 9:  // STOP
        break;
      case 10: {  // LIST: newline-joined table names (stats parity with
                  // the Python plane, which reports every table)
        std::lock_guard<std::mutex> lk(s->tables_mu);
        std::string names;
        for (auto& kv : s->tables) {
          if (!names.empty()) names += '\n';
          names += kv.first;
        }
        out.assign(names.begin(), names.end());
        break;
      }
      default:
        status = -1;
    }
    } catch (const std::exception&) {
      // bad_alloc etc. in a DETACHED thread would std::terminate the
      // whole host process; reply with an error and close instead
      status = -7;
      close_conn = true;
      out.clear();
    }

    {
      uint64_t plen = out.size();
      if (!write_n(fd, &status, 8) || !write_n(fd, &plen, 8)) break;
      if (plen && !write_n(fd, out.data(), plen)) break;
    }
    if (close_conn) break;
    if (op == 9) {
      s->stop.store(true);
      s->barrier_cv.notify_all();
      ::shutdown(s->listen_fd, SHUT_RDWR);
      break;
    }
  }
done:
  ::close(fd);
  std::lock_guard<std::mutex> lk(s->fds_mu);
  for (auto it = s->client_fds.begin(); it != s->client_fds.end(); ++it) {
    if (*it == fd) {
      s->client_fds.erase(it);
      break;
    }
  }
}

void ps_accept_loop(Server* s) {
  for (;;) {
    int fd = ::accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (s->stop.load()) return;
      // EMFILE/ENFILE etc. persist — don't busy-spin a core while the
      // worker threads still serve live connections
      ::usleep(10000);
      continue;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    {
      std::lock_guard<std::mutex> lk(s->fds_mu);
      s->client_fds.push_back(fd);
    }
    // detached + counted: an unjoined std::thread per connection would
    // leak stacks/TCBs under reconnect churn; shutdown waits on the
    // counter instead of join
    s->active_workers.fetch_add(1);
    std::thread([s, fd] {
      serve_client(s, fd);
      s->active_workers.fetch_sub(1);
    }).detach();
  }
}

// ---- client-side request helper ----

int64_t ps_request(int fd, uint8_t op, const char* name,
                   const uint8_t* head, uint64_t head_len, uint64_t n,
                   const uint8_t* body, uint64_t body_len, uint8_t* out,
                   uint64_t out_cap, uint64_t* out_len) {
  uint32_t nlen = static_cast<uint32_t>(std::strlen(name));
  if (!write_n(fd, &op, 1) || !write_n(fd, &nlen, 4)) return -100;
  if (nlen && !write_n(fd, name, nlen)) return -100;
  if (!write_n(fd, &n, 8)) return -100;
  if (head_len && !write_n(fd, head, head_len)) return -100;
  if (body_len && !write_n(fd, body, body_len)) return -100;
  int64_t status;
  uint64_t plen;
  if (!read_n(fd, &status, 8) || !read_n(fd, &plen, 8)) return -100;
  if (out_len) *out_len = plen;
  if (plen) {
    std::vector<uint8_t> buf(plen);
    if (!read_n(fd, buf.data(), plen)) return -100;
    uint64_t c = plen < out_cap ? plen : out_cap;
    if (out && c) std::memcpy(out, buf.data(), c);
  }
  return status;
}

}  // namespace

extern "C" {

void* pst_server_start(uint16_t port, uint32_t server_idx,
                       const char* host) {
  auto* s = new Server();
  s->server_idx = server_idx;
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  if (host && *host && std::strcmp(host, "0.0.0.0") != 0) {
    // bind the configured endpoint interface (the Python plane binds the
    // endpoint host too); hostname or dotted-quad
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (::getaddrinfo(host, nullptr, &hints, &res) == 0 && res) {
      addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
      ::freeaddrinfo(res);
    }
  }
  addr.sin_port = htons(port);
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(s->listen_fd, 128) != 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  s->thread = std::thread(ps_accept_loop, s);
  return s;
}

uint16_t pst_server_port(void* sp) {
  auto* s = static_cast<Server*>(sp);
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  ::getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
  return ntohs(addr.sin_port);
}

int pst_server_stopped(void* sp) {
  return static_cast<Server*>(sp)->stop.load() ? 1 : 0;
}

// Restore rows from .psbin files written by SAVE (this shard's suffix).
// Missing tables are created with default cfg + the file's dim, matching
// the Python plane's load_model contract.
int64_t pst_server_load(void* sp, const char* dirname, const char* table,
                        uint8_t opt, float lr) {
  auto* s = static_cast<Server*>(sp);
  std::string path = std::string(dirname) + "/" + table + ".shard" +
                     std::to_string(s->server_idx) + ".psbin";
  FILE* f = std::fopen(path.c_str(), "rb");
  if (!f) return -2;
  uint32_t dim;
  uint64_t n;
  if (std::fread(&dim, 4, 1, f) != 1 || std::fread(&n, 8, 1, f) != 1) {
    std::fclose(f);
    return -3;
  }
  Table* t;
  {
    std::lock_guard<std::mutex> lk(s->tables_mu);
    auto it = s->tables.find(table);
    if (it == s->tables.end()) {
      t = new Table();
      t->cfg.dim = dim;
      t->cfg.opt = opt;
      t->cfg.lr = lr;
      s->tables[table] = t;
    } else {
      t = it->second;
    }
  }
  std::lock_guard<std::mutex> tl(t->mu);
  // an existing table keeps its cfg — a file with a DIFFERENT dim would
  // leave rows shorter than cfg.dim, and later PULL/PUSH memcpys would
  // run past the row buffer (mirrors the CREATE adopt check, -4)
  if (t->cfg.dim != dim) {
    std::fclose(f);
    return -4;
  }
  uint64_t loaded = 0;
  for (; loaded < n; ++loaded) {
    int64_t rid;
    if (std::fread(&rid, 8, 1, f) != 1) break;
    Row r;
    r.w.resize(dim);
    if (std::fread(r.w.data(), 4, dim, f) != dim) break;  // partial row
    t->rows[rid] = std::move(r);                          // never stored
  }
  std::fclose(f);
  // a truncated file (crash/full disk mid-save) is an ERROR, not a
  // short success — silently re-initializing the missing rows would be
  // a partial, inconsistent restore
  return loaded == n ? static_cast<int64_t>(n) : -4;
}

void pst_server_stop(void* sp) {
  auto* s = static_cast<Server*>(sp);
  s->stop.store(true);
  s->barrier_cv.notify_all();
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  {
    std::lock_guard<std::mutex> lk(s->fds_mu);
    for (int fd : s->client_fds) ::shutdown(fd, SHUT_RDWR);
  }
  if (s->thread.joinable()) s->thread.join();
  // detached workers: wait (bounded) for the active counter — their fds
  // were shut down above, so recv() returns and they exit promptly
  for (int i = 0; i < 500 && s->active_workers.load() > 0; ++i)
    ::usleep(10000);
  {
    std::lock_guard<std::mutex> lk(s->tables_mu);
    for (auto& kv : s->tables) delete kv.second;
  }
  delete s;
}

// ---- client ----

void* pst_connect(const char* host, uint16_t port) {
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  char portstr[8];
  std::snprintf(portstr, sizeof(portstr), "%u", static_cast<unsigned>(port));
  if (::getaddrinfo(host, portstr, &hints, &res) != 0 || res == nullptr)
    return nullptr;
  int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0 || ::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    if (fd >= 0) ::close(fd);
    ::freeaddrinfo(res);
    return nullptr;
  }
  ::freeaddrinfo(res);
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return new int(fd);
}

void pst_close(void* cp) {
  int* fd = static_cast<int*>(cp);
  ::close(*fd);
  delete fd;
}

int64_t pst_create_table(void* cp, const char* name, uint32_t dim,
                         uint8_t opt, uint8_t init_kind, uint64_t seed,
                         float lr, float b1, float b2, float eps,
                         float init_range) {
  TableCfg cfg;
  cfg.dim = dim;
  cfg.opt = opt;
  cfg.init_kind = init_kind;
  cfg.seed = seed;
  cfg.lr = lr;
  cfg.b1 = b1;
  cfg.b2 = b2;
  cfg.eps = eps;
  cfg.init_range = init_range;
  return ps_request(*static_cast<int*>(cp), 0, name,
                    reinterpret_cast<const uint8_t*>(&cfg), sizeof(cfg), 0,
                    nullptr, 0, nullptr, 0, nullptr);
}

int64_t pst_pull_sparse(void* cp, const char* name, uint64_t n,
                        const int64_t* ids, float* out, uint32_t dim) {
  std::vector<uint8_t> resp(4 + n * static_cast<uint64_t>(dim) * 4);
  uint64_t got = 0;
  int64_t st = ps_request(*static_cast<int*>(cp), 1, name, nullptr, 0, n,
                          reinterpret_cast<const uint8_t*>(ids), n * 8,
                          resp.data(), resp.size(), &got);
  if (st < 0) return st;
  uint32_t sdim;
  std::memcpy(&sdim, resp.data(), 4);
  if (sdim != dim || got != resp.size()) return -5;
  std::memcpy(out, resp.data() + 4, n * static_cast<uint64_t>(dim) * 4);
  return 0;
}

int64_t pst_push_sparse(void* cp, const char* name, uint64_t n, uint32_t dim,
                        const int64_t* ids, const float* grads) {
  std::vector<uint8_t> body(n * 8 + n * static_cast<uint64_t>(dim) * 4);
  std::memcpy(body.data(), ids, n * 8);
  std::memcpy(body.data() + n * 8, grads, n * static_cast<uint64_t>(dim) * 4);
  return ps_request(*static_cast<int*>(cp), 2, name,
                    reinterpret_cast<const uint8_t*>(&dim), 4, n, body.data(),
                    body.size(), nullptr, 0, nullptr);
}

int64_t pst_dense_init(void* cp, const char* name, uint64_t n,
                       const float* v) {
  return ps_request(*static_cast<int*>(cp), 3, name, nullptr, 0, n,
                    reinterpret_cast<const uint8_t*>(v), n * 4, nullptr, 0,
                    nullptr);
}

int64_t pst_dense_pull(void* cp, const char* name, float* out,
                       uint64_t out_cap_floats, uint64_t* out_n) {
  uint64_t got = 0;
  int64_t st = ps_request(*static_cast<int*>(cp), 4, name, nullptr, 0, 0,
                          nullptr, 0, reinterpret_cast<uint8_t*>(out),
                          out_cap_floats * 4, &got);
  if (out_n) *out_n = got / 4;
  return st;
}

int64_t pst_dense_push(void* cp, const char* name, float lr, uint64_t n,
                       const float* g) {
  return ps_request(*static_cast<int*>(cp), 5, name,
                    reinterpret_cast<const uint8_t*>(&lr), 4, n,
                    reinterpret_cast<const uint8_t*>(g), n * 4, nullptr, 0,
                    nullptr);
}

int64_t pst_barrier(void* cp, const char* name, uint32_t world) {
  return ps_request(*static_cast<int*>(cp), 6, name, nullptr, 0, world,
                    nullptr, 0, nullptr, 0, nullptr);
}

int64_t pst_save(void* cp, const char* dirname) {
  return ps_request(*static_cast<int*>(cp), 7, dirname, nullptr, 0, 0,
                    nullptr, 0, nullptr, 0, nullptr);
}

int64_t pst_stats(void* cp, const char* name) {
  return ps_request(*static_cast<int*>(cp), 8, name, nullptr, 0, 0, nullptr,
                    0, nullptr, 0, nullptr);
}

int64_t pst_stop(void* cp) {
  return ps_request(*static_cast<int*>(cp), 9, "", nullptr, 0, 0, nullptr, 0,
                    nullptr, 0, nullptr);
}

int64_t pst_list_tables(void* cp, uint8_t* out, uint64_t out_cap,
                        uint64_t* out_len) {
  return ps_request(*static_cast<int*>(cp), 10, "", nullptr, 0, 0, nullptr,
                    0, out, out_cap, out_len);
}

}  // extern "C"
