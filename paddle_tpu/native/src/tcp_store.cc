// TCP key-value store for multi-host rendezvous.
//
// TPU-native analog of the reference TCPStore
// (paddle/phi/core/distributed/store/tcp_store.h:120, tcp_utils.cc): rank 0
// runs the server; clients set/get/wait/add keys to bootstrap process
// groups. On TPU the jax.distributed coordinator normally plays this role —
// this store covers the reference API surface (core.TCPStore) and any
// out-of-band bootstrap (elastic manager, launch controller).
//
// Protocol (all little-endian):
//   request:  u8 op | u32 klen | k bytes | u64 arg/vlen | v bytes
//     op: 0=SET 1=GET 2=ADD 3=WAIT 4=PING
//   response: i64 status/value | u64 vlen | v bytes
//     error statuses: -1 stopped-before-set, -3 SET value > 64 MiB
//     (reply then close — the unread payload would desync the stream),
//     -4 server-side exception (reply then close). A key > 4 KiB is a
//     protocol violation: the connection closes with NO reply.
// GET on a missing key blocks server-side until set (like reference wait).

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <exception>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

// Wire-supplied sizes are untrusted (same hardening as ps_table.cc): a
// huge klen/vlen would bad_alloc inside a server thread, and an uncaught
// exception in ANY std::thread std::terminate()s the whole process —
// which is the trainer, since the store runs in-process over ctypes.
constexpr uint32_t kMaxKeyLen = 4096;
constexpr uint64_t kMaxValLen = 64ull << 20;  // rendezvous blobs are small

struct Server {
  int listen_fd = -1;
  std::thread thread;
  std::atomic<bool> stop{false};
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::vector<uint8_t>> kv;
  std::map<std::string, int64_t> counters;
  std::vector<std::thread> workers;
  std::mutex fds_mu;
  std::vector<int> client_fds;  // open connections, shut down on stop
};

bool read_n(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= r;
  }
  return true;
}

bool write_n(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= r;
  }
  return true;
}

void serve_client(Server* s, int fd) {
  for (;;) {
    uint8_t op;
    uint32_t klen;
    if (!read_n(fd, &op, 1) || !read_n(fd, &klen, 4)) break;
    if (klen > kMaxKeyLen) break;  // protocol violation: close
    std::string key(klen, '\0');
    if (klen && !read_n(fd, key.data(), klen)) break;
    uint64_t arg;
    if (!read_n(fd, &arg, 8)) break;
    auto reply_and_close = [fd](int64_t st) {
      uint64_t zero = 0;
      write_n(fd, &st, 8);
      write_n(fd, &zero, 8);
    };
    if (op == 0 && arg > kMaxValLen) {
      // reply in-protocol, then close: the unread value bytes would be
      // parsed as the next request otherwise
      reply_and_close(-3);
      break;
    }
    std::vector<uint8_t> val;
    try {
      val.resize(op == 0 ? arg : 0);
    } catch (const std::exception&) {
      reply_and_close(-4);  // within-cap bad_alloc: never terminate
      break;
    }
    if (op == 0 && arg && !read_n(fd, val.data(), arg)) break;

    bool close_conn = false;
    int64_t status = 0;
    std::vector<uint8_t> out;
    try {
    if (op == 0) {  // SET
      std::lock_guard<std::mutex> lk(s->mu);
      s->kv[key] = std::move(val);
      s->cv.notify_all();
    } else if (op == 1 || op == 3) {  // GET / WAIT
      std::unique_lock<std::mutex> lk(s->mu);
      s->cv.wait(lk, [&] { return s->kv.count(key) || s->stop.load(); });
      if (s->stop.load() && !s->kv.count(key)) {
        status = -1;
      } else if (op == 1) {
        out = s->kv[key];
      }
    } else if (op == 2) {  // ADD (returns new counter value)
      std::lock_guard<std::mutex> lk(s->mu);
      s->counters[key] += static_cast<int64_t>(arg);
      status = s->counters[key];
    }  // op 4 PING: status 0
    } catch (const std::exception&) {
      status = -4;  // bad_alloc etc.: reply + close, never terminate
      close_conn = true;
      out.clear();
    }

    uint64_t vlen = out.size();
    if (!write_n(fd, &status, 8) || !write_n(fd, &vlen, 8)) break;
    if (vlen && !write_n(fd, out.data(), vlen)) break;
    if (close_conn) break;
  }
  ::close(fd);
  std::lock_guard<std::mutex> lk(s->fds_mu);
  for (auto it = s->client_fds.begin(); it != s->client_fds.end(); ++it) {
    if (*it == fd) {
      s->client_fds.erase(it);
      break;
    }
  }
}

void accept_loop(Server* s) {
  for (;;) {
    int fd = ::accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (s->stop.load()) return;
      continue;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    {
      std::lock_guard<std::mutex> lk(s->fds_mu);
      s->client_fds.push_back(fd);
    }
    s->workers.emplace_back(serve_client, s, fd);
  }
}

}  // namespace

extern "C" {

// Returns server handle, or null on bind failure. port=0 picks a free port;
// ts_port() reports it.
void* ts_server_start(uint16_t port) {
  auto* s = new Server();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(s->listen_fd, 128) != 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  s->thread = std::thread(accept_loop, s);
  return s;
}

uint16_t ts_port(void* sp) {
  auto* s = static_cast<Server*>(sp);
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  ::getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
  return ntohs(addr.sin_port);
}

void ts_server_stop(void* sp) {
  auto* s = static_cast<Server*>(sp);
  s->stop.store(true);
  s->cv.notify_all();
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  // wake worker threads parked in recv() on live client connections —
  // without this, join() below deadlocks while any client stays connected
  {
    std::lock_guard<std::mutex> lk(s->fds_mu);
    for (int fd : s->client_fds) ::shutdown(fd, SHUT_RDWR);
  }
  if (s->thread.joinable()) s->thread.join();
  for (auto& w : s->workers)
    if (w.joinable()) w.join();
  delete s;
}

// ---- client ----

void* ts_client_connect(const char* host, uint16_t port) {
  // hostname OR dotted-quad (MASTER_ADDR is usually a hostname in clusters)
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  char portstr[8];
  std::snprintf(portstr, sizeof(portstr), "%u", static_cast<unsigned>(port));
  if (::getaddrinfo(host, portstr, &hints, &res) != 0 || res == nullptr) {
    return nullptr;
  }
  int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0 || ::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    if (fd >= 0) ::close(fd);
    ::freeaddrinfo(res);
    return nullptr;
  }
  ::freeaddrinfo(res);
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* h = new int(fd);
  return h;
}

void ts_client_close(void* cp) {
  int* fd = static_cast<int*>(cp);
  ::close(*fd);
  delete fd;
}

static int64_t request(int fd, uint8_t op, const char* key, uint32_t klen,
                       const uint8_t* val, uint64_t vlen, uint8_t* out,
                       uint64_t out_cap, uint64_t* out_len) {
  // precheck BEFORE any bytes go out: the server would close on these,
  // and a partial request would desync the stream for the caller's next
  // use of this handle
  if (klen > kMaxKeyLen) return -3;
  if (op == 0 && vlen > kMaxValLen) return -3;
  if (!write_n(fd, &op, 1) || !write_n(fd, &klen, 4)) return -2;
  if (klen && !write_n(fd, key, klen)) return -2;
  if (!write_n(fd, &vlen, 8)) return -2;
  if (op == 0 && vlen && !write_n(fd, val, vlen)) return -2;
  int64_t status;
  uint64_t rlen;
  if (!read_n(fd, &status, 8) || !read_n(fd, &rlen, 8)) return -2;
  if (rlen > kMaxValLen) {
    // malformed peer: don't bad_alloc, and poison the now-desynced fd
    // so a retry on this handle fails like any dead socket
    ::shutdown(fd, SHUT_RDWR);
    return -2;
  }
  if (out_len) *out_len = rlen;
  if (rlen) {
    std::vector<uint8_t> buf(rlen);
    if (!read_n(fd, buf.data(), rlen)) return -2;
    uint64_t n = rlen < out_cap ? rlen : out_cap;
    if (out && n) std::memcpy(out, buf.data(), n);
  }
  return status;
}

int64_t ts_set(void* cp, const char* key, const uint8_t* val, uint64_t vlen) {
  return request(*static_cast<int*>(cp), 0, key, std::strlen(key), val, vlen,
                 nullptr, 0, nullptr);
}

int64_t ts_get(void* cp, const char* key, uint8_t* out, uint64_t out_cap,
               uint64_t* out_len) {
  return request(*static_cast<int*>(cp), 1, key, std::strlen(key), nullptr, 0,
                 out, out_cap, out_len);
}

int64_t ts_add(void* cp, const char* key, int64_t amount) {
  return request(*static_cast<int*>(cp), 2, key, std::strlen(key), nullptr,
                 static_cast<uint64_t>(amount), nullptr, 0, nullptr);
}

int64_t ts_wait(void* cp, const char* key) {
  return request(*static_cast<int*>(cp), 3, key, std::strlen(key), nullptr, 0,
                 nullptr, 0, nullptr);
}

}  // extern "C"
