// Lock-light host span recorder.
//
// TPU-native analog of the reference HostEventRecorder ring buffer
// (paddle/fluid/platform/profiler/host_event_recorder.h): per-thread local
// chunks appended under a short lock, drained once at profiler stop. Span
// names are interned to uint32 ids on the Python side; records are fixed
// 24-byte structs so draining is one memcpy.

#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

struct Span {
  uint32_t name_id;
  uint32_t tid;
  uint64_t start_ns;
  uint64_t end_ns;
};

struct Tracer {
  explicit Tracer(size_t cap) : capacity(cap) { spans.reserve(1024); }
  size_t capacity;
  std::vector<Span> spans;
  std::mutex mu;
  uint64_t dropped = 0;
};

}  // namespace

extern "C" {

void* ht_create(uint64_t capacity) { return new Tracer(capacity); }

void ht_destroy(void* t) { delete static_cast<Tracer*>(t); }

void ht_record(void* tp, uint32_t name_id, uint32_t tid, uint64_t start_ns,
               uint64_t end_ns) {
  auto* t = static_cast<Tracer*>(tp);
  std::lock_guard<std::mutex> lk(t->mu);
  if (t->spans.size() >= t->capacity) {
    ++t->dropped;
    return;
  }
  t->spans.push_back(Span{name_id, tid, start_ns, end_ns});
}

uint64_t ht_count(void* tp) {
  auto* t = static_cast<Tracer*>(tp);
  std::lock_guard<std::mutex> lk(t->mu);
  return t->spans.size();
}

// Drain up to max_spans into out (layout: 4+4+8+8 bytes per span, packed).
uint64_t ht_drain(void* tp, uint8_t* out, uint64_t max_spans) {
  auto* t = static_cast<Tracer*>(tp);
  std::lock_guard<std::mutex> lk(t->mu);
  uint64_t n = t->spans.size() < max_spans ? t->spans.size() : max_spans;
  std::memcpy(out, t->spans.data(), n * sizeof(Span));
  t->spans.erase(t->spans.begin(), t->spans.begin() + n);
  return n;
}

uint64_t ht_dropped(void* tp) {
  return static_cast<Tracer*>(tp)->dropped;
}

}  // extern "C"
