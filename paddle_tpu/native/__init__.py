"""Native (C++) runtime components, ctypes-bound.

The reference implements its runtime substrate in C++ (SURVEY.md §2
``[native]`` rows); this package provides the TPU build's equivalents where
Python would be the wrong tool:

- BlockingQueue  — bounded MPMC queue (data-pipeline backpressure,
  ≙ operators/reader/blocking_queue.h)
- HostTracer     — fixed-record span ring buffer
  (≙ platform/profiler/host_event_recorder.h)
- TCPStore       — TCP rendezvous KV server/client
  (≙ phi/core/distributed/store/tcp_store.cc)

Built on first import with g++ (no pybind11 in this image — plain C ABI via
ctypes). If the toolchain or build fails, ``AVAILABLE`` is False and pure-
Python fallbacks in the consumers take over.
"""
from __future__ import annotations

import ctypes
import os
import pickle
import subprocess
import threading
from typing import Optional

__all__ = ["AVAILABLE", "BlockingQueue", "HostTracer", "TCPStore",
           "TCPStoreServer", "lib_path"]

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LIB = None
AVAILABLE = False


def _sources():
    return sorted(
        os.path.join(_SRC_DIR, f) for f in os.listdir(_SRC_DIR)
        if f.endswith(".cc"))


def _build() -> Optional[str]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    out = os.path.join(_BUILD_DIR, "libpaddle_tpu_native.so")
    srcs = _sources()
    stamp = os.path.join(_BUILD_DIR, "stamp")
    # -ffp-contract=off: g++'s default 'fast' fuses fp expressions into
    # FMAs, breaking the bit-exact cross-plane row-init contract between
    # ps_table.cc and the numpy implementation (distributed/ps)
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
           "-ffp-contract=off", "-o", out] + srcs
    # the stamp covers the COMMAND too: a flag change (e.g. the
    # load-bearing -ffp-contract) must trigger a rebuild, not silently
    # reuse a stale .so
    sig = str([(s, os.path.getmtime(s)) for s in srcs]) + str(cmd)
    if os.path.exists(out) and os.path.exists(stamp):
        with open(stamp) as f:
            if f.read() == sig:
                return out
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.SubprocessError, FileNotFoundError):
        return None
    with open(stamp, "w") as f:
        f.write(sig)
    return out


def lib_path() -> Optional[str]:
    return _build()


def _load():
    global _LIB, AVAILABLE
    if _LIB is not None:
        return _LIB
    path = _build()
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    # blocking queue
    lib.bq_create.restype = ctypes.c_void_p
    lib.bq_create.argtypes = [ctypes.c_uint64]
    lib.bq_destroy.argtypes = [ctypes.c_void_p]
    lib.bq_push.restype = ctypes.c_int
    lib.bq_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    lib.bq_pop.restype = ctypes.c_int64
    lib.bq_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
    lib.bq_peek_size.restype = ctypes.c_int64
    lib.bq_peek_size.argtypes = [ctypes.c_void_p]
    lib.bq_close.argtypes = [ctypes.c_void_p]
    lib.bq_size.restype = ctypes.c_uint64
    lib.bq_size.argtypes = [ctypes.c_void_p]
    # host tracer
    lib.ht_create.restype = ctypes.c_void_p
    lib.ht_create.argtypes = [ctypes.c_uint64]
    lib.ht_destroy.argtypes = [ctypes.c_void_p]
    lib.ht_record.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                              ctypes.c_uint32, ctypes.c_uint64,
                              ctypes.c_uint64]
    lib.ht_count.restype = ctypes.c_uint64
    lib.ht_count.argtypes = [ctypes.c_void_p]
    lib.ht_drain.restype = ctypes.c_uint64
    lib.ht_drain.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                             ctypes.c_uint64]
    lib.ht_dropped.restype = ctypes.c_uint64
    lib.ht_dropped.argtypes = [ctypes.c_void_p]
    # tcp store
    lib.ts_server_start.restype = ctypes.c_void_p
    lib.ts_server_start.argtypes = [ctypes.c_uint16]
    lib.ts_port.restype = ctypes.c_uint16
    lib.ts_port.argtypes = [ctypes.c_void_p]
    lib.ts_server_stop.argtypes = [ctypes.c_void_p]
    lib.ts_client_connect.restype = ctypes.c_void_p
    lib.ts_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint16]
    lib.ts_client_close.argtypes = [ctypes.c_void_p]
    lib.ts_set.restype = ctypes.c_int64
    lib.ts_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                           ctypes.c_uint64]
    lib.ts_get.restype = ctypes.c_int64
    lib.ts_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                           ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64)]
    lib.ts_add.restype = ctypes.c_int64
    lib.ts_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.ts_wait.restype = ctypes.c_int64
    lib.ts_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    # parameter-server data plane (ps_table.cc)
    lib.pst_server_start.restype = ctypes.c_void_p
    lib.pst_server_start.argtypes = [ctypes.c_uint16, ctypes.c_uint32,
                                     ctypes.c_char_p]
    lib.pst_server_port.restype = ctypes.c_uint16
    lib.pst_server_port.argtypes = [ctypes.c_void_p]
    lib.pst_server_stopped.restype = ctypes.c_int
    lib.pst_server_stopped.argtypes = [ctypes.c_void_p]
    lib.pst_server_load.restype = ctypes.c_int64
    lib.pst_server_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_char_p, ctypes.c_uint8,
                                    ctypes.c_float]
    lib.pst_server_stop.argtypes = [ctypes.c_void_p]
    lib.pst_connect.restype = ctypes.c_void_p
    lib.pst_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint16]
    lib.pst_close.argtypes = [ctypes.c_void_p]
    lib.pst_create_table.restype = ctypes.c_int64
    lib.pst_create_table.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint8,
        ctypes.c_uint8, ctypes.c_uint64, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_float, ctypes.c_float]
    lib.pst_pull_sparse.restype = ctypes.c_int64
    lib.pst_pull_sparse.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64, ctypes.c_void_p,
                                    ctypes.c_void_p, ctypes.c_uint32]
    lib.pst_push_sparse.restype = ctypes.c_int64
    lib.pst_push_sparse.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64, ctypes.c_uint32,
                                    ctypes.c_void_p, ctypes.c_void_p]
    lib.pst_dense_init.restype = ctypes.c_int64
    lib.pst_dense_init.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64, ctypes.c_void_p]
    lib.pst_dense_pull.restype = ctypes.c_int64
    lib.pst_dense_pull.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.POINTER(ctypes.c_uint64)]
    lib.pst_dense_push.restype = ctypes.c_int64
    lib.pst_dense_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_float, ctypes.c_uint64,
                                   ctypes.c_void_p]
    lib.pst_barrier.restype = ctypes.c_int64
    lib.pst_barrier.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint32]
    lib.pst_save.restype = ctypes.c_int64
    lib.pst_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.pst_stats.restype = ctypes.c_int64
    lib.pst_stats.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.pst_stop.restype = ctypes.c_int64
    lib.pst_stop.argtypes = [ctypes.c_void_p]
    lib.pst_list_tables.restype = ctypes.c_int64
    lib.pst_list_tables.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_uint64,
                                    ctypes.POINTER(ctypes.c_uint64)]
    _LIB = lib
    AVAILABLE = True
    return lib


class BlockingQueue:
    """Bounded queue of picklable items over the native blob queue."""

    def __init__(self, capacity: int = 8):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._q = lib.bq_create(capacity)
        # peek_size + pop must be one unit per consumer: two threads
        # interleaving them would size the buffer off a DIFFERENT blob
        self._pop_mu = threading.Lock()

    def push(self, item) -> bool:
        blob = pickle.dumps(item, protocol=pickle.HIGHEST_PROTOCOL)
        return self._lib.bq_push(self._q, blob, len(blob)) == 0

    def pop(self):
        with self._pop_mu:
            size = self._lib.bq_peek_size(self._q)
            if size < 0:
                raise EOFError("queue closed")
            buf = ctypes.create_string_buffer(size)
            n = self._lib.bq_pop(self._q, buf, size)
        if n < 0:
            raise EOFError("queue closed")
        return pickle.loads(buf.raw[:n])

    def close(self):
        self._lib.bq_close(self._q)

    def __len__(self):
        return int(self._lib.bq_size(self._q))

    def __del__(self):
        try:
            self._lib.bq_destroy(self._q)
        except Exception:
            pass


class HostTracer:
    """Interned-name span recorder over the native ring buffer."""

    _RECORD = 24  # u32 name_id + u32 tid + u64 start + u64 end

    def __init__(self, capacity: int = 1_000_000):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._t = lib.ht_create(capacity)
        self._names = {}
        self._rev = []
        self._lock = threading.Lock()

    def _intern(self, name: str) -> int:
        with self._lock:
            i = self._names.get(name)
            if i is None:
                i = len(self._rev)
                self._names[name] = i
                self._rev.append(name)
            return i

    def record(self, name: str, start_ns: int, end_ns: int, tid: int = 0):
        self._lib.ht_record(self._t, self._intern(name), tid & 0xFFFFFFFF,
                            start_ns, end_ns)

    def drain(self):
        import struct

        n = int(self._lib.ht_count(self._t))
        if not n:
            return []
        buf = ctypes.create_string_buffer(n * self._RECORD)
        got = int(self._lib.ht_drain(self._t, buf, n))
        out = []
        for i in range(got):
            name_id, tid, s, e = struct.unpack_from("<IIQQ", buf,
                                                    i * self._RECORD)
            out.append((self._rev[name_id], s, e, tid))
        return out

    @property
    def dropped(self) -> int:
        return int(self._lib.ht_dropped(self._t))

    def __del__(self):
        try:
            self._lib.ht_destroy(self._t)
        except Exception:
            pass


class TCPStoreServer:
    def __init__(self, port: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._s = lib.ts_server_start(port)
        if not self._s:
            raise OSError(f"TCPStore bind failed on port {port}")
        self.port = int(lib.ts_port(self._s))

    def stop(self):
        if self._s:
            self._lib.ts_server_stop(self._s)
            self._s = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class TCPStore:
    """Client mirroring the reference core.TCPStore API (set/get/add/wait).
    is_master=True also starts the server in-process (rank-0 pattern,
    parallel.py:1077)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: int = 900):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._server = None
        if is_master:
            self._server = TCPStoreServer(port)
            port = self._server.port
        self.host, self.port = host, port
        # non-master ranks usually race the master's bind: retry within
        # `timeout` (reference TCPStore connect loop, tcp_utils.cc)
        import time as _time

        deadline = _time.monotonic() + timeout
        delay = 0.05
        self._c = None
        while True:
            self._c = lib.ts_client_connect(host.encode(), port)
            if self._c:
                break
            if is_master or _time.monotonic() >= deadline:
                raise ConnectionError(
                    f"TCPStore connect to {host}:{port} failed")
            _time.sleep(delay)
            delay = min(delay * 2, 2.0)
        # one socket per client: serialize requests (a heartbeat thread and
        # the main thread interleaving writes would corrupt the protocol)
        self._mu = threading.Lock()

    def set(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode()
        with self._mu:
            rc = self._lib.ts_set(self._c, key.encode(), bytes(value),
                                  len(value))
        if rc != 0:
            raise IOError("TCPStore set failed")

    def get(self, key: str) -> bytes:
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        out_len = ctypes.c_uint64(0)
        with self._mu:
            rc = self._lib.ts_get(self._c, key.encode(), buf, cap,
                                  ctypes.byref(out_len))
        if rc != 0:
            raise KeyError(key)
        return buf.raw[: out_len.value]

    def add(self, key: str, amount: int = 1) -> int:
        if amount < 0:
            # counters in this store are nonnegative BY CONTRACT: ADD's
            # result rides the status channel, and the error space below
            # is only distinguishable from counter values because real
            # counts can never be negative. A negative amount could walk
            # a counter into [-4, -1] and masquerade as an IO error.
            raise ValueError(
                f"TCPStore.add amount must be nonnegative, got {amount} "
                "(counters start at 0 and only grow; negative results "
                "are reserved for transport errors)")
        with self._mu:
            rc = int(self._lib.ts_add(self._c, key.encode(), amount))
        if rc < 0 and rc >= -4:
            # transport/server errors (-2 io, -3 over-cap key, -4 server
            # exception) — distinguishable from counts because counters
            # are nonnegative (enforced above). Returning them as counts
            # would hand barrier code a bogus rank.
            k = key if len(key) <= 64 else key[:61] + "..."
            raise OSError(f"TCPStore add({k!r}) failed: rc={rc}")
        return rc

    def wait(self, key: str) -> None:
        # NOTE: wait blocks server-side; holding the lock would starve other
        # threads of this client, so waiters should use their own client.
        with self._mu:
            if self._lib.ts_wait(self._c, key.encode()) != 0:
                raise TimeoutError(f"wait({key}) failed")

    def close(self):
        if self._c:
            self._lib.ts_client_close(self._c)
            self._c = None
        if self._server is not None:
            self._server.stop()
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
