"""paddle.utils.download (reference: utils/download.py
get_weights_path_from_url): cache-dir resolution + fetch. This image has
ZERO egress, so a cache MISS raises an actionable error instead of
half-downloading; cache hits (pre-seeded weights) work normally."""
from __future__ import annotations

import os
from typing import Optional

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/weights")


def get_weights_path_from_url(url: str, md5sum: Optional[str] = None) -> str:
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)


def get_path_from_url(url: str, root_dir: str,
                      md5sum: Optional[str] = None,
                      check_exist: bool = True) -> str:
    fname = os.path.basename(url.split("?")[0])
    path = os.path.join(root_dir, fname)
    if check_exist and os.path.isfile(path):
        return path
    try:
        import urllib.request

        os.makedirs(root_dir, exist_ok=True)
        urllib.request.urlretrieve(url, path)  # noqa: S310
        return path
    except Exception as e:
        raise RuntimeError(
            f"could not download {url!r} (this environment may have no "
            f"network egress); pre-seed the file at {path!r} instead"
        ) from e
