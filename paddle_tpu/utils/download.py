"""paddle.utils.download (reference: utils/download.py
get_weights_path_from_url): cache-dir resolution + fetch. This image has
ZERO egress, so a cache MISS raises an actionable error instead of
half-downloading; cache hits (pre-seeded weights) work normally."""
from __future__ import annotations

import hashlib
import os
from typing import Optional

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/weights")


def _md5_matches(path: str, md5sum: Optional[str]) -> bool:
    if md5sum is None:
        return True
    h = hashlib.md5()  # noqa: S324 - integrity check, not security
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest() == md5sum


def get_weights_path_from_url(url: str, md5sum: Optional[str] = None) -> str:
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)


def get_path_from_url(url: str, root_dir: str,
                      md5sum: Optional[str] = None,
                      check_exist: bool = True) -> str:
    fname = os.path.basename(url.split("?")[0])
    path = os.path.join(root_dir, fname)
    stale = False
    if check_exist and os.path.isfile(path):
        if _md5_matches(path, md5sum):
            return path
        stale = True  # keep the file until a good replacement exists
    # download to a temp path; only replace the cache entry on success so
    # a failed re-fetch never destroys a pre-seeded file
    tmp = path + ".part"
    try:
        import urllib.request

        os.makedirs(root_dir, exist_ok=True)
        urllib.request.urlretrieve(url, tmp)  # noqa: S310
    except Exception as e:
        if os.path.isfile(tmp):
            os.remove(tmp)
        detail = (f"cached file failed md5 check ({md5sum}) and "
                  if stale else "")
        raise RuntimeError(
            f"could not download {url!r}: {detail}this environment may "
            f"have no network egress; pre-seed the file at {path!r} instead"
        ) from e
    if not _md5_matches(tmp, md5sum):
        os.remove(tmp)
        raise RuntimeError(
            f"md5 mismatch for downloaded {url!r}: expected {md5sum}")
    os.replace(tmp, path)
    return path
