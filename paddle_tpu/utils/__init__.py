"""paddle.utils parity (reference: python/paddle/utils/__init__.py —
__all__ = deprecated, run_check, require_version, try_import; plus the
unique_name / dlpack / download submodule surface).

TPU-native notes: run_check exercises the actual accelerator path (a
jitted matmul on every visible device) instead of the reference's CUDA
install probe; dlpack rides jax's zero-copy dlpack bridge.
"""
from __future__ import annotations

import functools
import importlib
import re
import warnings
from typing import Optional

from . import cpp_extension, dlpack, download, unique_name  # noqa: F401

__all__ = ["deprecated", "run_check", "require_version", "try_import",
           "unique_name", "dlpack", "download", "cpp_extension"]


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 0):
    """Decorator marking an API deprecated (reference
    utils/deprecated.py): warns once per site; level>=2 raises."""

    def deco(fn):
        msg = f"API '{getattr(fn, '__name__', fn)}' is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f"; use {update_to} instead"
        if reason:
            msg += f" ({reason})"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if level >= 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        wrapper.__deprecated__ = msg
        return wrapper

    return deco


def run_check():
    """Install check (reference utils/install_check.py run_check): run a
    jitted matmul on the visible devices and report."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices()
    x = jnp.asarray(np.random.RandomState(0).randn(8, 8, ).astype("float32"))
    # lint: allow-recompile(one-shot install diagnostic — compiling IS
    # the thing being checked; never on a serving path)
    out = jax.jit(lambda a: a @ a)(x)
    out.block_until_ready()
    print(f"PaddlePaddle (TPU-native) works on {len(devs)} "
          f"{devs[0].platform} device(s).")
    if len(devs) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(devs), ("d",))
        y = jax.device_put(x, NamedSharding(mesh, P("d")))
        # lint: allow-recompile(same one-shot diagnostic, sharded arm)
        jax.jit(lambda a: a * 2)(y).block_until_ready()
        print(f"PaddlePaddle (TPU-native) works on {len(devs)} devices "
              f"in parallel.")


def _parse_ver(v: str):
    return [int(p) for p in re.findall(r"\d+", v)[:4]]


def require_version(min_version: str, max_version: Optional[str] = None):
    """Check the installed framework version is within range (reference
    utils/__init__ require_version)."""
    import paddle_tpu

    cur = _parse_ver(paddle_tpu.__version__)
    if min_version is not None and cur < _parse_ver(str(min_version)):
        raise Exception(
            f"installed version {paddle_tpu.__version__} < required "
            f"minimum {min_version}")
    if max_version is not None and cur > _parse_ver(str(max_version)):
        raise Exception(
            f"installed version {paddle_tpu.__version__} > allowed "
            f"maximum {max_version}")
    return True


def try_import(module_name: str, err_msg: Optional[str] = None):
    """Import or raise with an actionable message (reference
    utils/lazy_import.try_import)."""
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"module {module_name!r} is required but not "
            f"installed (and this environment forbids pip install — gate "
            f"the feature instead)") from e
