"""paddle.utils.cpp_extension — JIT-compiled C++ custom ops.

Reference: python/paddle/utils/cpp_extension/cpp_extension.py ``load``
(:799, subprocess-free JIT compile + module return) and ``setup`` (:79),
with ops authored against the C++ extension ABI (PD_BUILD_OP,
paddle/phi/api/ext/op_meta_info.h:874; phi/capi for the C kernel ABI).

TPU-native redesign: a custom C++ op cannot run ON the TPU — device
kernels are Pallas (``paddle_tpu.ops``), authored in Python. What the
extension point genuinely provides on TPU is HOST custom ops: CPU math,
data preparation, tokenizers. So:

- user code compiles against the small stable C ABI in
  ``paddle_tpu_ext.h`` (the phi/capi role): one exported function per op,
  ``PT_KERNEL(name) { ... }`` over ``PTExtBuffer`` views;
- :func:`load` g++-compiles sources to a shared library (content-hash
  cached), binds the exported ops via ctypes, and returns a module-like
  handle whose ops are callable BOTH eagerly and inside ``jax.jit``
  (lowered as ``jax.pure_callback`` — XLA schedules the host call);
- a ``<name>_grad`` export, if present, becomes the op's VJP
  (``PD_BUILD_GRAD_OP`` analog): it receives the forward inputs plus the
  output cotangent and writes input cotangents.

``setup``/``CppExtension``/``BuildExtension`` cover the installable-
wheel flavor through setuptools. ``CUDAExtension`` raises: no CUDA
toolchain targets a TPU image (write Pallas instead).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Callable, List, Optional, Sequence

import numpy as np

__all__ = ["load", "setup", "CppExtension", "CUDAExtension",
           "BuildExtension", "get_build_directory"]

_HEADER_DIR = os.path.dirname(os.path.abspath(__file__))

_DTYPES = {
    np.dtype(np.float32): 0, np.dtype(np.float64): 1,
    np.dtype(np.int32): 2, np.dtype(np.int64): 3,
    np.dtype(np.bool_): 4, np.dtype(np.uint8): 5,
}


def get_build_directory(verbose: bool = False) -> str:
    """Reference extension_utils.get_build_directory: honors
    PADDLE_EXTENSION_DIR, else a per-user temp dir."""
    root = os.environ.get("PADDLE_EXTENSION_DIR") or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(root, exist_ok=True)
    return root


class _Buffer(ctypes.Structure):
    _fields_ = [("data", ctypes.c_void_p),
                ("shape", ctypes.POINTER(ctypes.c_int64)),
                ("ndim", ctypes.c_int32),
                ("dtype", ctypes.c_int32),
                ("numel", ctypes.c_int64)]


def _make_buffer(arr: np.ndarray, keepalive: list) -> _Buffer:
    arr = np.ascontiguousarray(arr)
    shape = (ctypes.c_int64 * max(arr.ndim, 1))(*(arr.shape or (0,)))
    keepalive.extend((arr, shape))
    return _Buffer(arr.ctypes.data_as(ctypes.c_void_p), shape,
                   arr.ndim, _DTYPES[arr.dtype], arr.size)


class ExtensionOp:
    """One bound custom op: callable eagerly and under jit."""

    def __init__(self, lib, name: str, out_shapes: Callable,
                 grad_name: Optional[str] = None):
        self._name = name
        self._fn = getattr(lib, name)
        self._fn.restype = ctypes.c_int
        self._fn.argtypes = [ctypes.c_int, ctypes.POINTER(_Buffer),
                             ctypes.c_int, ctypes.POINTER(_Buffer)]
        self._out_shapes = out_shapes
        self._grad = None
        if grad_name is not None:
            self._grad = getattr(lib, grad_name)
            self._grad.restype = ctypes.c_int
            self._grad.argtypes = self._fn.argtypes

    # raw host execution over numpy arrays
    def _run(self, fn, inputs: Sequence[np.ndarray],
             out_specs) -> List[np.ndarray]:
        keep: list = []
        in_bufs = (_Buffer * len(inputs))(
            *[_make_buffer(np.asarray(x), keep) for x in inputs])
        outs = [np.zeros(s.shape, s.dtype) for s in out_specs]
        out_bufs = (_Buffer * len(outs))(
            *[_make_buffer(o, keep) for o in outs])
        # _make_buffer copies only if non-contiguous; outs are fresh and
        # contiguous, so the kernel writes THESE arrays
        rc = fn(len(inputs), in_bufs, len(outs), out_bufs)
        if rc != 0:
            raise RuntimeError(
                f"custom op {self._name!r} returned error code {rc}")
        return outs

    @staticmethod
    def _spec(v):
        import jax

        if hasattr(v, "dtype") and hasattr(v, "shape"):  # array OR tracer
            return jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
        a = np.asarray(v)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)

    def __call__(self, *args):
        import jax

        from ...core.tensor import Tensor

        vals = [a._value if isinstance(a, Tensor) else a for a in args]
        specs = [self._spec(v) for v in vals]
        out_specs = self._out_shapes(*specs)
        single = not isinstance(out_specs, (tuple, list))
        if single:
            out_specs = (out_specs,)
        out_specs = tuple(out_specs)

        if self._grad is not None:
            out = self._with_grad(vals, out_specs, single)
        else:
            def host(*arrs):
                outs = self._run(self._fn, arrs, out_specs)
                return outs[0] if single else tuple(outs)

            out = jax.pure_callback(host, out_specs[0] if single
                                    else tuple(out_specs), *vals)
        return Tensor(out) if any(isinstance(a, Tensor) for a in args) \
            else out

    def _with_grad(self, vals, out_specs, single):
        import jax

        grad_fn = self._grad
        in_specs = tuple(self._spec(v) for v in vals)

        @jax.custom_vjp
        def op(*xs):
            def host(*arrs):
                outs = self._run(self._fn, arrs, out_specs)
                return outs[0] if single else tuple(outs)

            return jax.pure_callback(host, out_specs[0] if single
                                     else tuple(out_specs), *xs)

        def op_fwd(*xs):
            return op(*xs), xs

        def op_bwd(xs, g):
            gs = (g,) if single else tuple(g)

            def host(*arrs):
                return tuple(self._run(grad_fn, arrs, in_specs))

            outs = jax.pure_callback(host, in_specs, *(tuple(xs) + gs))
            return tuple(outs)

        op.defvjp(op_fwd, op_bwd)
        return op(*vals)


class ExtensionModule:
    """What :func:`load` returns: ops as attributes (reference parity —
    ``module.custom_relu(x)``)."""

    def __init__(self, lib, path: str):
        self._lib = lib
        self._path = path
        self._ops = {}

    def def_op(self, name: str,
               out_shapes: Optional[Callable] = None,
               has_grad: Optional[bool] = None) -> ExtensionOp:
        """Bind an exported kernel. ``out_shapes(*in_specs)`` returns the
        output ShapeDtypeStruct(s); default = same as input 0 (the
        elementwise contract). ``has_grad`` defaults to auto-detecting a
        ``<name>_grad`` export."""
        if out_shapes is None:
            out_shapes = lambda *specs: specs[0]  # noqa: E731
        if has_grad is None:
            has_grad = hasattr(self._lib, f"{name}_grad")
        op = ExtensionOp(self._lib, name, out_shapes,
                         f"{name}_grad" if has_grad else None)
        self._ops[name] = op
        setattr(self, name, op)
        return op


def _compile(name: str, sources: Sequence[str], extra_cxx_cflags,
             extra_ldflags, extra_include_paths, build_directory,
             verbose: bool) -> str:
    build = build_directory or get_build_directory()
    blob = hashlib.sha1()
    for s in sources:
        blob.update(open(s, "rb").read())
    blob.update(" ".join(extra_cxx_cflags or []).encode())
    so = os.path.join(build, f"{name}_{blob.hexdigest()[:12]}.so")
    if not os.path.exists(so):
        cmd = (["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                f"-I{_HEADER_DIR}"]
               + [f"-I{p}" for p in (extra_include_paths or [])]
               + list(extra_cxx_cflags or []) + list(sources)
               + list(extra_ldflags or []) + ["-o", so])
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"custom op compilation failed:\n{proc.stderr}")
    return so


def load(name: str, sources: Sequence[str], extra_cxx_cflags=None,
         extra_cuda_cflags=None, extra_ldflags=None,
         extra_include_paths=None, extra_library_paths=None,
         build_directory=None, verbose: bool = False) -> ExtensionModule:
    """JIT-compile C++ sources into a custom-op module (reference
    cpp_extension.load:799 — no setup.py, no CMake/Ninja). Ops are bound
    with :meth:`ExtensionModule.def_op`; elementwise single-output ops
    with a ``<name>_grad`` export need nothing else."""
    if extra_cuda_cflags:
        raise ValueError(
            "CUDA sources are not supported on a TPU image; write device "
            "kernels in Pallas (paddle_tpu.ops) instead")
    ld = list(extra_ldflags or [])
    for p in (extra_library_paths or []):
        ld.append(f"-L{p}")
    so = _compile(name, sources, extra_cxx_cflags, ld,
                  extra_include_paths, build_directory, verbose)
    return ExtensionModule(ctypes.CDLL(so), so)


class CppExtension:
    """setuptools flavor (reference cpp_extension.CppExtension): returns a
    configured setuptools.Extension pointing at the ABI header."""

    def __new__(cls, sources, *args, **kwargs):
        from setuptools import Extension

        kwargs.setdefault("include_dirs", []).append(_HEADER_DIR)
        kwargs.setdefault("language", "c++")
        name = kwargs.pop("name", "paddle_tpu_custom_ops")
        return Extension(name, sources, *args, **kwargs)


def CUDAExtension(*args, **kwargs):
    raise RuntimeError(
        "CUDAExtension targets nvcc, which does not exist on a TPU "
        "image; write device kernels in Pallas (paddle_tpu.ops) and host "
        "ops against paddle_tpu_ext.h via CppExtension/load")


def BuildExtension(*args, **kwargs):
    """Reference BuildExtension.with_options analog: the plain setuptools
    build_ext already handles our C++-only extensions."""
    from setuptools.command.build_ext import build_ext

    if args or kwargs:
        return build_ext
    return build_ext


def setup(**attr):
    """Reference cpp_extension.setup:79 — setuptools.setup with the
    custom-op build wiring (build_ext + our extensions)."""
    from setuptools import setup as _setup

    attr.setdefault("cmdclass", {})["build_ext"] = BuildExtension()
    return _setup(**attr)
