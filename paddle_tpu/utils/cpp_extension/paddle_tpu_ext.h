/* paddle_tpu custom-op C ABI (the phi/capi role, TPU-native form).
 *
 * Reference analog: paddle/phi/capi exposes a C kernel ABI so user ops
 * compile against a stable surface (PD_BUILD_OP, paddle/phi/api/ext/
 * op_meta_info.h:874). On TPU, device kernels are Pallas (Python-side);
 * the C ABI covers HOST ops: custom CPU math, data prep, tokenizers —
 * anything that runs as a host callback inside or outside jit.
 *
 * Contract: export  `int <name>(int n_in, const PTExtBuffer* in,
 *                               int n_out, PTExtBuffer* out)`
 * with C linkage. Inputs are read-only; outputs are pre-allocated by the
 * framework according to the op's registered output shapes. Return 0 on
 * success, nonzero to raise RuntimeError in Python.
 */
#ifndef PADDLE_TPU_EXT_H_
#define PADDLE_TPU_EXT_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef enum {
  PT_FLOAT32 = 0,
  PT_FLOAT64 = 1,
  PT_INT32 = 2,
  PT_INT64 = 3,
  PT_BOOL = 4,
  PT_UINT8 = 5,
} PTExtDtype;

typedef struct {
  void* data;            /* contiguous, C order */
  const int64_t* shape;  /* ndim entries */
  int32_t ndim;
  int32_t dtype;         /* PTExtDtype */
  int64_t numel;
} PTExtBuffer;

#define PT_KERNEL(name)                                                    \
  int name(int n_in, const PTExtBuffer* in, int n_out, PTExtBuffer* out)

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_EXT_H_ */
