"""paddle.utils.dlpack (reference: utils/dlpack.py to_dlpack/from_dlpack)
over jax's zero-copy dlpack bridge — the interop path to torch/numpy."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor → DLPack capsule. jax arrays implement __dlpack__; torch &
    numpy consume it zero-copy (device permitting)."""
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return v.__dlpack__()


def from_dlpack(capsule) -> Tensor:
    """DLPack capsule (or any __dlpack__ object, e.g. a torch tensor) →
    Tensor."""
    return Tensor(jnp.from_dlpack(capsule))
