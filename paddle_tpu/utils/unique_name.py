"""paddle.utils.unique_name (reference: utils/unique_name.py —
generate/guard/switch over per-prefix counters)."""
from __future__ import annotations

import contextlib
from typing import Dict, Optional

__all__ = ["generate", "guard", "switch"]


class _Generator:
    def __init__(self):
        self.ids: Dict[str, int] = {}

    def __call__(self, key: str) -> str:
        n = self.ids.get(key, 0)
        self.ids[key] = n + 1
        return f"{key}_{n}"


_generator = _Generator()


def generate(key: str) -> str:
    return _generator(key)


def switch(new_generator: Optional[_Generator] = None):
    """Swap the counter table; returns the old one (reference switch)."""
    global _generator
    old = _generator
    _generator = new_generator if new_generator is not None else _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Fresh naming scope (reference guard): names inside restart from 0;
    the outer table is restored on exit."""
    if isinstance(new_generator, str):
        # reference allows a prefix string: namespaced fresh generator
        prefix = new_generator

        class _Prefixed(_Generator):
            def __call__(self, key):
                return super().__call__(f"{prefix}{key}")

        old = switch(_Prefixed())
    else:
        old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
