"""Deterministic fault injection at the serving-path seams.

The fault-isolated serving layer (per-request containment, supervised
engine recovery, the stall watchdog — ``paddle_tpu.serving``) is only
trustworthy if its failure paths are exercised deterministically; this
module is the harness that does it. A :class:`FaultPlan` is a schedule
of site-named injections (raise / hang / fail-on-nth-call, plus a
seeded probabilistic mode for chaos soaks), and :class:`FaultyEngine`
is a transparent proxy over a generation engine that consults the plan
at each seam before delegating.

Sites (the seams a serving scheduler drives):

- ``"admit"``   — ``add_request`` / ``begin_admit`` (the admission call
  seam: the fault fires BEFORE the engine claims any capacity);
- ``"prefill"`` — the engine's internal prefill dispatch
  (``_run_prefill``), i.e. INSIDE ``add_request`` after the slot (and,
  paged, the page reservation) was claimed — exercises the admission
  abort guards, not just the call seam;
- ``"chunk"``   — ``admit_chunk`` (one chunk of a chunked admission);
- ``"decode"``  — ``decode_segment`` (the batch-wide seam: an injected
  :class:`~paddle_tpu.inference.generation.EngineFault` here drives the
  supervised-recovery path, a hang drives the stall watchdog);
- ``"collect"`` — ``collect_finished``;
- ``"preempt"`` — ``preempt_request`` (the paged engine's
  memory-pressure victim reclaim: a fault here hits the scheduler's
  pressure-relief loop mid-preemption — the window where a victim's
  slot/pages reclaim and its replay parking must stay atomic under
  recovery).

Determinism: every seam call increments a per-site counter under a
lock, and rules fire on exact 1-based call indices (``nth``/``times``),
so a single-threaded scheduler drives a bit-identical fault schedule
run over run. The probabilistic mode (:meth:`FaultPlan.random_raises`)
draws from a seeded ``random.Random`` per rule — deterministic given
the seed and the call sequence.

Usage::

    from paddle_tpu.testing.faults import FaultPlan, FaultyEngine
    from paddle_tpu.inference.generation import EngineFault

    plan = FaultPlan()
    plan.raise_at("prefill", nth=2)                  # request-scoped
    plan.raise_at("decode", nth=3,
                  exc=EngineFault("injected"))       # engine-scoped
    plan.hang_at("decode", nth=5, seconds=2.0)       # stall watchdog
    eng = FaultyEngine(inner_engine, plan)
    srv = Server(eng, ...)
    ...
    assert plan.injected == [("prefill", 2, "raise"), ...]
"""
from __future__ import annotations

import random
import threading
from typing import List, Optional, Sequence

from .. import tracing as trace

__all__ = ["SITES", "FaultPlan", "FaultyEngine", "InjectedFault"]

SITES = ("admit", "prefill", "chunk", "decode", "collect", "preempt")


class InjectedFault(RuntimeError):
    """Default exception an injection raises. Deliberately NOT a
    :class:`RequestFault`/:class:`EngineFault` subclass: it takes the
    site-default classification, like any unrecognized error — pass
    ``exc=EngineFault(...)`` to force the engine-scoped path."""


class _Rule:
    __slots__ = ("site", "first", "times", "action", "exc", "seconds",
                 "rate", "rng", "fired")

    def __init__(self, site: str, first: int, times: int, action: str,
                 exc=None, seconds: float = 0.0,
                 rate: Optional[float] = None, seed: int = 0):
        if site not in SITES:
            raise ValueError(f"unknown site {site!r}; one of {SITES}")
        if first < 1 or times < 1:
            raise ValueError("nth and times must be >= 1")
        self.site = site
        self.first = first        # 1-based call index the rule arms at
        self.times = times        # injections before the rule retires
        self.action = action      # "raise" | "hang"
        self.exc = exc            # instance, class, or None (default)
        self.seconds = seconds
        self.rate = rate          # probabilistic (chaos-soak) rule
        self.rng = random.Random(seed) if rate is not None else None
        self.fired = 0


class FaultPlan:
    """A deterministic schedule of injections, shared by every seam of
    one (or several) :class:`FaultyEngine`.

    - :meth:`raise_at` — raise at the ``nth`` call to a site (and the
      ``times - 1`` calls after it);
    - :meth:`hang_at` — block the calling (scheduler) thread for
      ``seconds`` — bounded, and releasable early via
      :meth:`release_hangs`, so a chaos test can never wedge the suite;
    - :meth:`random_raises` — seeded per-call coin flip, the chaos-soak
      mode ``tools/serve_bench.py --fault-rate`` drives;
    - ``plan.injected`` — the ``(site, call_index, action)`` log, for
      assertions and BENCH records;
    - ``plan.calls`` — per-site call counters (how often each seam ran).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: List[_Rule] = []
        self.calls = {s: 0 for s in SITES}
        self.injected: List[tuple] = []
        self._release = threading.Event()

    # -- schedule construction (chainable) -----------------------------------
    def raise_at(self, site: str, nth: int = 1, exc=None,
                 times: int = 1) -> "FaultPlan":
        """Raise ``exc`` (default :class:`InjectedFault`) at calls
        ``nth .. nth+times-1`` to ``site``."""
        with self._lock:
            self._rules.append(_Rule(site, nth, times, "raise", exc))
        return self

    def hang_at(self, site: str, nth: int = 1, seconds: float = 1.0,
                times: int = 1) -> "FaultPlan":
        """Block for ``seconds`` at calls ``nth .. nth+times-1`` to
        ``site`` (then delegate normally — a hang is a stall, not a
        failure). :meth:`release_hangs` ends every hang early."""
        with self._lock:
            self._rules.append(
                _Rule(site, nth, times, "hang", seconds=seconds))
        return self

    def random_raises(self, sites: Sequence[str], rate: float,
                      seed: int = 0, exc=None) -> "FaultPlan":
        """Chaos-soak mode: at every call to each of ``sites``, raise
        with probability ``rate`` (seeded — deterministic given the
        call sequence)."""
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {rate}")
        with self._lock:
            for i, site in enumerate(sites):
                self._rules.append(
                    _Rule(site, 1, 2 ** 31, "raise", exc,
                          rate=rate, seed=seed + i))
        return self

    def kill(self, site: str = "decode", nth: int = 1, exc=None,
             action: str = "raise",
             seconds: float = 3600.0) -> "FaultPlan":
        """REPLICA-KILL seam: from call ``nth`` (1-based; relative to
        calls already made, so a mid-run ``plan.kill()`` fires on the
        very next seam call) the replica is DEAD — every subsequent
        call to ``site`` raises a fresh
        :class:`~paddle_tpu.inference.generation.EngineFault` (default
        ``exc``; pass a class/factory to change it). Behind a
        ``Server(max_restarts=0)`` the first fault kills the replica's
        scheduler; with restarts left, every recovery re-faults until
        the budget exhausts — either way the replica ends ``failed``,
        which is what a router's supervision and failover must absorb.
        ``action="hang"`` is the WEDGED variant (each call blocks
        ``seconds``, releasable via :meth:`release_hangs`) — drives
        the watchdog-degraded path a router abandons without the
        replica ever announcing failure.

        Callable mid-run from any thread (the bench's
        ``--kill-replica-at`` timer): the rule lands under the plan
        lock like any other."""
        if action not in ("raise", "hang"):
            raise ValueError(
                f"action must be 'raise' or 'hang', got {action!r}")
        if exc is None and action == "raise":
            from ..inference.generation import EngineFault
            exc = (lambda: EngineFault(
                f"replica killed (injected @ {site})"))
        with self._lock:
            # arm relative to the CURRENT call count: "kill now" means
            # the next call, not the nth since the dawn of the plan
            first = self.calls.get(site, 0) + nth
            self._rules.append(
                _Rule(site, first, 2 ** 31, action, exc,
                      seconds=seconds))
        return self

    def release_hangs(self) -> None:
        """End every in-flight (and future) hang immediately."""
        self._release.set()

    # -- the seam hook -------------------------------------------------------
    def fire(self, site: str) -> None:
        """Called by :class:`FaultyEngine` before delegating a seam
        call: count the call, and perform the first matching un-retired
        rule's action (raise / hang)."""
        with self._lock:
            self.calls[site] = self.calls.get(site, 0) + 1
            n = self.calls[site]
            rule = None
            for r in self._rules:
                if r.site != site or r.fired >= r.times:
                    continue
                if r.rate is not None:
                    if r.rng.random() < r.rate:
                        rule = r
                        break
                elif n >= r.first:
                    rule = r
                    break
            if rule is None:
                return
            rule.fired += 1
            self.injected.append((site, n, rule.action))
            action, exc, seconds = rule.action, rule.exc, rule.seconds
        if trace.enabled():
            # injections are part of the story a flight dump tells: a
            # chaos postmortem must distinguish injected faults from
            # organic ones
            trace.event("fault.injected", site=site, call=n,
                        action=action)
        if action == "hang":
            # outside the lock: a hung scheduler must not also wedge
            # every other seam's bookkeeping
            self._release.wait(seconds)
            return
        if exc is None:
            raise InjectedFault(f"injected fault @ {site} (call {n})")
        if isinstance(exc, BaseException):
            # an INSTANCE is re-raised as-is — fine for single-shot
            # deterministic rules; repeating rules (times>1, random)
            # should pass a class or zero-arg factory so every
            # injection gets a fresh instance (re-raising one object
            # chains tracebacks onto it forever)
            raise exc
        raise exc()   # class or zero-arg factory


class FaultyEngine:
    """Transparent proxy over a continuous-batching engine that fires
    ``plan`` at each serving-path seam before delegating. Everything
    else (capacity probes, ``partial_tokens``, ``warmup``,
    ``reset_state``, attributes) passes straight through, so a serving
    :class:`~paddle_tpu.serving.Server` drives it unchanged.

    The ``"prefill"`` site is hooked INSIDE the wrapped engine (its
    ``_run_prefill`` dispatch is shadowed on the instance) so the fault
    fires after admission capacity was claimed — the path that must
    prove the abort guards reclaim the slot and pages. ``warmup`` is
    unaffected (it drives the jitted programs directly, not the
    dispatch helpers)."""

    _SEAMS = {"add_request": "admit", "begin_admit": "admit",
              "admit_chunk": "chunk", "decode_segment": "decode",
              "collect_finished": "collect"}

    def __init__(self, engine, plan: FaultPlan):
        object.__setattr__(self, "_engine", engine)
        object.__setattr__(self, "plan", plan)
        orig = engine._run_prefill

        def faulty_prefill(*a, **kw):
            self.plan.fire("prefill")
            return orig(*a, **kw)

        engine._run_prefill = faulty_prefill

    def __getattr__(self, name):
        return getattr(self._engine, name)

    def __setattr__(self, name, value):
        # proxy-owned state stays on the proxy (reassigning ``plan``
        # between scenarios must rearm the seams, not write a dead
        # attribute onto the engine); every OTHER write routes to the
        # wrapped engine (e.g. the Server's admission_mode convenience
        # setter) — a proxy-local shadow would leave the inner engine
        # on its old policy while reads through the proxy claimed
        # otherwise
        if name in ("plan", "_engine"):
            object.__setattr__(self, name, value)
        else:
            setattr(self._engine, name, value)

    def add_request(self, *a, **kw):
        self.plan.fire("admit")
        return self._engine.add_request(*a, **kw)

    def begin_admit(self, *a, **kw):
        self.plan.fire("admit")
        return self._engine.begin_admit(*a, **kw)

    def admit_chunk(self, *a, **kw):
        self.plan.fire("chunk")
        return self._engine.admit_chunk(*a, **kw)

    def decode_segment(self, *a, **kw):
        self.plan.fire("decode")
        return self._engine.decode_segment(*a, **kw)

    def collect_finished(self, *a, **kw):
        self.plan.fire("collect")
        return self._engine.collect_finished(*a, **kw)

    def preempt_request(self, *a, **kw):
        self.plan.fire("preempt")
        return self._engine.preempt_request(*a, **kw)
