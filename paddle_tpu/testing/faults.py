"""Deterministic fault injection at the serving-path seams.

The fault-isolated serving layer (per-request containment, supervised
engine recovery, the stall watchdog — ``paddle_tpu.serving``) is only
trustworthy if its failure paths are exercised deterministically; this
module is the harness that does it. A :class:`FaultPlan` is a schedule
of site-named injections (raise / hang / fail-on-nth-call, plus a
seeded probabilistic mode for chaos soaks), and :class:`FaultyEngine`
is a transparent proxy over a generation engine that consults the plan
at each seam before delegating.

Sites (the seams a serving scheduler drives):

- ``"admit"``   — ``add_request`` / ``begin_admit`` (the admission call
  seam: the fault fires BEFORE the engine claims any capacity);
- ``"prefill"`` — the engine's internal prefill dispatch
  (``_run_prefill``), i.e. INSIDE ``add_request`` after the slot (and,
  paged, the page reservation) was claimed — exercises the admission
  abort guards, not just the call seam;
- ``"chunk"``   — ``admit_chunk`` (one chunk of a chunked admission);
- ``"decode"``  — ``decode_segment`` (the batch-wide seam: an injected
  :class:`~paddle_tpu.inference.generation.EngineFault` here drives the
  supervised-recovery path, a hang drives the stall watchdog);
- ``"collect"`` — ``collect_finished``;
- ``"preempt"`` — ``preempt_request`` (the paged engine's
  memory-pressure victim reclaim: a fault here hits the scheduler's
  pressure-relief loop mid-preemption — the window where a victim's
  slot/pages reclaim and its replay parking must stay atomic under
  recovery).

Determinism: every seam call increments a per-site counter under a
lock, and rules fire on exact 1-based call indices (``nth``/``times``),
so a single-threaded scheduler drives a bit-identical fault schedule
run over run. The probabilistic mode (:meth:`FaultPlan.random_raises`)
draws from a seeded ``random.Random`` per rule — deterministic given
the seed and the call sequence.

Usage::

    from paddle_tpu.testing.faults import FaultPlan, FaultyEngine
    from paddle_tpu.inference.generation import EngineFault

    plan = FaultPlan()
    plan.raise_at("prefill", nth=2)                  # request-scoped
    plan.raise_at("decode", nth=3,
                  exc=EngineFault("injected"))       # engine-scoped
    plan.hang_at("decode", nth=5, seconds=2.0)       # stall watchdog
    eng = FaultyEngine(inner_engine, plan)
    srv = Server(eng, ...)
    ...
    assert plan.injected == [("prefill", 2, "raise"), ...]
"""
from __future__ import annotations

import random
import threading
from typing import List, Optional, Sequence

from .. import tracing as trace

__all__ = ["SITES", "NET_SITES", "FaultPlan", "NetworkFaultPlan",
           "FaultyEngine", "InjectedFault"]

SITES = ("admit", "prefill", "chunk", "decode", "collect", "preempt")

# network seams (cross-process serving, paddle_tpu.serving.remote):
# a SEPARATE namespace from the engine SITES — a RemoteReplica's
# failure modes are the wire's (delay / drop / mid-stream half-close),
# not the engine's, and the two plans never share counters
NET_SITES = ("generate", "kv_import")


class InjectedFault(RuntimeError):
    """Default exception an injection raises. Deliberately NOT a
    :class:`RequestFault`/:class:`EngineFault` subclass: it takes the
    site-default classification, like any unrecognized error — pass
    ``exc=EngineFault(...)`` to force the engine-scoped path."""


class _Rule:
    __slots__ = ("site", "first", "times", "action", "exc", "seconds",
                 "rate", "rng", "fired", "after", "mode")

    def __init__(self, site: str, first: int, times: int, action: str,
                 exc=None, seconds: float = 0.0,
                 rate: Optional[float] = None, seed: int = 0,
                 after: int = 0, mode: Optional[str] = None,
                 valid_sites: Sequence[str] = SITES):
        if site not in valid_sites:
            raise ValueError(
                f"unknown site {site!r}; one of {tuple(valid_sites)}")
        if first < 1 or times < 1:
            raise ValueError("nth and times must be >= 1")
        self.site = site
        self.first = first        # 1-based call index the rule arms at
        self.times = times        # injections before the rule retires
        self.action = action      # "raise" | "hang"
        self.exc = exc            # instance, class, or None (default)
        self.seconds = seconds
        self.rate = rate          # probabilistic (chaos-soak) rule
        self.rng = random.Random(seed) if rate is not None else None
        self.fired = 0
        self.after = after        # half_close/corrupt: lines to relay
        self.mode = mode          # corrupt: "flip" | "truncate"


class FaultPlan:
    """A deterministic schedule of injections, shared by every seam of
    one (or several) :class:`FaultyEngine`.

    - :meth:`raise_at` — raise at the ``nth`` call to a site (and the
      ``times - 1`` calls after it);
    - :meth:`hang_at` — block the calling (scheduler) thread for
      ``seconds`` — bounded, and releasable early via
      :meth:`release_hangs`, so a chaos test can never wedge the suite;
    - :meth:`random_raises` — seeded per-call coin flip, the chaos-soak
      mode ``tools/serve_bench.py --fault-rate`` drives;
    - ``plan.injected`` — the ``(site, call_index, action)`` log, for
      assertions and BENCH records;
    - ``plan.calls`` — per-site call counters (how often each seam ran).
    """

    VALID_SITES: Sequence[str] = SITES

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: List[_Rule] = []
        self.calls = {s: 0 for s in self.VALID_SITES}
        self.injected: List[tuple] = []
        self._release = threading.Event()

    # -- schedule construction (chainable) -----------------------------------
    def raise_at(self, site: str, nth: int = 1, exc=None,
                 times: int = 1) -> "FaultPlan":
        """Raise ``exc`` (default :class:`InjectedFault`) at calls
        ``nth .. nth+times-1`` to ``site``."""
        with self._lock:
            self._rules.append(_Rule(site, nth, times, "raise", exc,
                                     valid_sites=self.VALID_SITES))
        return self

    def hang_at(self, site: str, nth: int = 1, seconds: float = 1.0,
                times: int = 1) -> "FaultPlan":
        """Block for ``seconds`` at calls ``nth .. nth+times-1`` to
        ``site`` (then delegate normally — a hang is a stall, not a
        failure). :meth:`release_hangs` ends every hang early."""
        with self._lock:
            self._rules.append(
                _Rule(site, nth, times, "hang", seconds=seconds,
                      valid_sites=self.VALID_SITES))
        return self

    def random_raises(self, sites: Sequence[str], rate: float,
                      seed: int = 0, exc=None) -> "FaultPlan":
        """Chaos-soak mode: at every call to each of ``sites``, raise
        with probability ``rate`` (seeded — deterministic given the
        call sequence)."""
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {rate}")
        with self._lock:
            for i, site in enumerate(sites):
                self._rules.append(
                    _Rule(site, 1, 2 ** 31, "raise", exc,
                          rate=rate, seed=seed + i,
                          valid_sites=self.VALID_SITES))
        return self

    def kill(self, site: str = "decode", nth: int = 1, exc=None,
             action: str = "raise",
             seconds: float = 3600.0) -> "FaultPlan":
        """REPLICA-KILL seam: from call ``nth`` (1-based; relative to
        calls already made, so a mid-run ``plan.kill()`` fires on the
        very next seam call) the replica is DEAD — every subsequent
        call to ``site`` raises a fresh
        :class:`~paddle_tpu.inference.generation.EngineFault` (default
        ``exc``; pass a class/factory to change it). Behind a
        ``Server(max_restarts=0)`` the first fault kills the replica's
        scheduler; with restarts left, every recovery re-faults until
        the budget exhausts — either way the replica ends ``failed``,
        which is what a router's supervision and failover must absorb.
        ``action="hang"`` is the WEDGED variant (each call blocks
        ``seconds``, releasable via :meth:`release_hangs`) — drives
        the watchdog-degraded path a router abandons without the
        replica ever announcing failure.

        Callable mid-run from any thread (the bench's
        ``--kill-replica-at`` timer): the rule lands under the plan
        lock like any other."""
        if action not in ("raise", "hang"):
            raise ValueError(
                f"action must be 'raise' or 'hang', got {action!r}")
        if exc is None and action == "raise":
            from ..inference.generation import EngineFault
            exc = (lambda: EngineFault(
                f"replica killed (injected @ {site})"))
        with self._lock:
            # arm relative to the CURRENT call count: "kill now" means
            # the next call, not the nth since the dawn of the plan
            first = self.calls.get(site, 0) + nth
            self._rules.append(
                _Rule(site, first, 2 ** 31, action, exc,
                      seconds=seconds, valid_sites=self.VALID_SITES))
        return self

    def release_hangs(self) -> None:
        """End every in-flight (and future) hang immediately."""
        self._release.set()

    # -- the seam hook -------------------------------------------------------
    def _consume(self, site: str):
        """Count a call to ``site`` and consume the first matching
        un-retired rule: bump ``calls``, log to ``injected``, trace.
        Returns ``(action, exc, seconds, after, mode, n)`` or
        ``None``."""
        with self._lock:
            self.calls[site] = self.calls.get(site, 0) + 1
            n = self.calls[site]
            rule = None
            for r in self._rules:
                if r.site != site or r.fired >= r.times:
                    continue
                if r.rate is not None:
                    if r.rng.random() < r.rate:
                        rule = r
                        break
                elif n >= r.first:
                    rule = r
                    break
            if rule is None:
                return None
            rule.fired += 1
            self.injected.append((site, n, rule.action))
            hit = (rule.action, rule.exc, rule.seconds, rule.after,
                   rule.mode, n)
        if trace.enabled():
            # injections are part of the story a flight dump tells: a
            # chaos postmortem must distinguish injected faults from
            # organic ones
            trace.event("fault.injected", site=site, call=n,
                        action=hit[0])
        return hit

    def fire(self, site: str) -> None:
        """Called by :class:`FaultyEngine` before delegating a seam
        call: count the call, and perform the first matching un-retired
        rule's action (raise / hang)."""
        hit = self._consume(site)
        if hit is None:
            return
        action, exc, seconds, _after, _mode, n = hit
        if action == "hang":
            # outside the lock: a hung scheduler must not also wedge
            # every other seam's bookkeeping
            self._release.wait(seconds)
            return
        if exc is None:
            raise InjectedFault(f"injected fault @ {site} (call {n})")
        if isinstance(exc, BaseException):
            # an INSTANCE is re-raised as-is — fine for single-shot
            # deterministic rules; repeating rules (times>1, random)
            # should pass a class or zero-arg factory so every
            # injection gets a fresh instance (re-raising one object
            # chains tracebacks onto it forever)
            raise exc
        raise exc()   # class or zero-arg factory


class NetworkFaultPlan(FaultPlan):
    """Deterministic injections at the WIRE seams of a
    :class:`~paddle_tpu.serving.remote.RemoteReplica` — the failure
    modes a cross-process fleet must absorb are the network's, not the
    engine's, so they get their own site namespace (:data:`NET_SITES`)
    and their own plan (never share counters with an engine-side
    :class:`FaultPlan`).

    Sites:

    - ``"generate"``  — one ``POST /generate`` submission (counted at
      the client, before the request hits the wire);
    - ``"kv_import"`` — one ``POST /kv/import`` KV-page shipment (the
      disaggregated prefill→decode handoff).

    Actions, same nth/times discipline as the base plan:

    - :meth:`delay_at` — bounded stall before the call proceeds
      (releasable early via :meth:`release_hangs`, like a hang);
    - :meth:`drop_at` — the connection never happens: raises
      ``ConnectionResetError`` (or ``exc``) at the seam, which the
      client surfaces exactly like a refused/reset socket;
    - :meth:`half_close_at` — the INSIDIOUS one: the request goes
      through, the server streams, and the client-side reader kills
      the socket after relaying ``after`` stream lines — a mid-stream
      half-close the router's failover replay must absorb without the
      handle ever seeing a gap;
    - :meth:`corrupt_at` — the payload arrives, but WRONG: a
      deterministic byte-flip (well-framed, bit-rotted — only a
      checksum can tell) or truncation of the KV ship / token stream.
      The injection the integrity-checked wire is tested against.

    The seam hook is :meth:`fire`, which unlike the base plan RETURNS
    the half-close/corrupt spec (``{"action": "half_close", "after":
    n}`` / ``{"action": "corrupt", "mode": m, "after": n}``) instead
    of raising — the mangling happens later, inside the reader thread
    or the payload path, not at the call site. ``delay`` blocks then
    returns ``None``; ``drop`` raises. Inherited :meth:`raise_at` /
    :meth:`hang_at` also work against :data:`NET_SITES` (validation is
    class-driven)."""

    VALID_SITES = NET_SITES

    # -- schedule construction (chainable) -----------------------------------
    def delay_at(self, site: str, nth: int = 1, seconds: float = 0.05,
                 times: int = 1) -> "NetworkFaultPlan":
        """Bounded network delay: block ``seconds`` at calls
        ``nth .. nth+times-1`` to ``site``, then proceed normally.
        :meth:`release_hangs` ends every delay early."""
        with self._lock:
            self._rules.append(
                _Rule(site, nth, times, "delay", seconds=seconds,
                      valid_sites=self.VALID_SITES))
        return self

    def drop_at(self, site: str, nth: int = 1, exc=None,
                times: int = 1) -> "NetworkFaultPlan":
        """Drop the connection at calls ``nth .. nth+times-1``:
        raises ``ConnectionResetError`` (or ``exc``) at the seam."""
        with self._lock:
            self._rules.append(
                _Rule(site, nth, times, "drop", exc,
                      valid_sites=self.VALID_SITES))
        return self

    def half_close_at(self, site: str = "generate", nth: int = 1,
                      after: int = 1,
                      times: int = 1) -> "NetworkFaultPlan":
        """Mid-stream half-close: the ``nth`` call to ``site``
        proceeds, but the client tears the socket down after relaying
        ``after`` stream lines (1-based; ``after=2`` lets two ndjson
        lines through, then cuts)."""
        if after < 1:
            raise ValueError("after must be >= 1")
        with self._lock:
            self._rules.append(
                _Rule(site, nth, times, "half_close", after=after,
                      valid_sites=self.VALID_SITES))
        return self

    def corrupt_at(self, site: str, nth: int = 1, mode: str = "flip",
                   after: int = 1,
                   times: int = 1) -> "NetworkFaultPlan":
        """Deterministic payload corruption at the wire seam — the
        injection the KV integrity layer is tested against. Same
        no-real-sockets discipline as the other actions: the bytes are
        mangled at the client seam, never by a real middlebox.

        - ``mode="flip"`` — a byte-flip that keeps the framing intact:
          on ``kv_import`` the last payload byte (array bytes, past
          the header) is XOR'd, so only the checksum can tell; on
          ``generate`` the stream line after ``after`` relayed tokens
          arrives garbled (the reader sees torn ndjson).
        - ``mode="truncate"`` — the payload/stream ends early: on
          ``kv_import`` the framed body loses its tail (the receiver's
          geometry validation sees a truncated layer); on ``generate``
          it behaves like a half-close after ``after`` lines."""
        if mode not in ("flip", "truncate"):
            raise ValueError(
                f"mode must be 'flip' or 'truncate', got {mode!r}")
        if after < 1:
            raise ValueError("after must be >= 1")
        with self._lock:
            self._rules.append(
                _Rule(site, nth, times, "corrupt", after=after,
                      mode=mode, valid_sites=self.VALID_SITES))
        return self

    # -- the seam hook -------------------------------------------------------
    def fire(self, site: str):
        """Network-seam variant: ``delay`` blocks then returns
        ``None``; ``drop`` (and inherited ``raise``) raises;
        ``half_close`` / ``corrupt`` return their spec dict for the
        caller to carry into the stream reader / payload path.
        Returns ``None`` when no rule fires."""
        hit = self._consume(site)
        if hit is None:
            return None
        action, exc, seconds, after, mode, n = hit
        if action in ("hang", "delay"):
            self._release.wait(seconds)
            return None
        if action == "half_close":
            return {"action": "half_close", "after": after}
        if action == "corrupt":
            return {"action": "corrupt", "mode": mode, "after": after}
        if exc is None:
            if action == "drop":
                raise ConnectionResetError(
                    f"injected network drop @ {site} (call {n})")
            raise InjectedFault(f"injected fault @ {site} (call {n})")
        if isinstance(exc, BaseException):
            raise exc
        raise exc()   # class or zero-arg factory


class FaultyEngine:
    """Transparent proxy over a continuous-batching engine that fires
    ``plan`` at each serving-path seam before delegating. Everything
    else (capacity probes, ``partial_tokens``, ``warmup``,
    ``reset_state``, attributes) passes straight through, so a serving
    :class:`~paddle_tpu.serving.Server` drives it unchanged.

    The ``"prefill"`` site is hooked INSIDE the wrapped engine (its
    ``_run_prefill`` dispatch is shadowed on the instance) so the fault
    fires after admission capacity was claimed — the path that must
    prove the abort guards reclaim the slot and pages. ``warmup`` is
    unaffected (it drives the jitted programs directly, not the
    dispatch helpers)."""

    _SEAMS = {"add_request": "admit", "begin_admit": "admit",
              "admit_chunk": "chunk", "decode_segment": "decode",
              "collect_finished": "collect"}

    def __init__(self, engine, plan: FaultPlan):
        object.__setattr__(self, "_engine", engine)
        object.__setattr__(self, "plan", plan)
        orig = engine._run_prefill

        def faulty_prefill(*a, **kw):
            self.plan.fire("prefill")
            return orig(*a, **kw)

        engine._run_prefill = faulty_prefill

    def __getattr__(self, name):
        return getattr(self._engine, name)

    def __setattr__(self, name, value):
        # proxy-owned state stays on the proxy (reassigning ``plan``
        # between scenarios must rearm the seams, not write a dead
        # attribute onto the engine); every OTHER write routes to the
        # wrapped engine (e.g. the Server's admission_mode convenience
        # setter) — a proxy-local shadow would leave the inner engine
        # on its old policy while reads through the proxy claimed
        # otherwise
        if name in ("plan", "_engine"):
            object.__setattr__(self, name, value)
        else:
            setattr(self._engine, name, value)

    def add_request(self, *a, **kw):
        self.plan.fire("admit")
        return self._engine.add_request(*a, **kw)

    def begin_admit(self, *a, **kw):
        self.plan.fire("admit")
        return self._engine.begin_admit(*a, **kw)

    def admit_chunk(self, *a, **kw):
        self.plan.fire("chunk")
        return self._engine.admit_chunk(*a, **kw)

    def decode_segment(self, *a, **kw):
        self.plan.fire("decode")
        return self._engine.decode_segment(*a, **kw)

    def collect_finished(self, *a, **kw):
        self.plan.fire("collect")
        return self._engine.collect_finished(*a, **kw)

    def preempt_request(self, *a, **kw):
        self.plan.fire("preempt")
        return self._engine.preempt_request(*a, **kw)
