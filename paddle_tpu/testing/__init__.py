"""paddle_tpu.testing — deterministic test harnesses.

Production robustness features need reproducible misbehavior to test
against; this package holds the harnesses that create it. Today:
:mod:`~paddle_tpu.testing.faults` — deterministic, site-named fault
injection at the serving-path seams (admission, prefill, chunked
prefill, decode segment, collect, preempt, plus the replica-kill
``FaultPlan.kill`` seam the router suite drives), feeding the chaos
suites ``tests/test_serving_faults.py`` / ``tests/test_router.py``
and ``tools/serve_bench.py``'s ``--fault-rate`` /
``--kill-replica-at`` chaos knobs. :func:`retry_under_load` is the
shared wrapper for WALL-CLOCK-sensitive tests that are correct alone
but flaky when the whole suite has every core busy.
"""
import functools
import os
import time as _time

from .faults import SITES, FaultPlan, FaultyEngine, InjectedFault

__all__ = ["SITES", "FaultPlan", "FaultyEngine", "InjectedFault",
           "retry_under_load"]


def retry_under_load(fn=None, attempts=3):
    """Decorator for LOAD-flaky tests: ones that pass alone but can
    time out or miss a wall-clock bound when the full tier-1 run has
    every core busy (multiprocess workers starving behind the suite,
    watchdog/backoff timing asserted under a multi-replica router's
    thread load). Retry a couple of times with backoff; if the
    failure persists WHILE the box is demonstrably overloaded, xfail
    with the evidence instead of polluting the tier-1 signal — on an
    idle box the failure still fails loudly (a real regression must
    not hide behind the load excuse)."""
    if fn is None:
        return functools.partial(retry_under_load, attempts=attempts)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        last = None
        for attempt in range(attempts):
            try:
                return fn(*args, **kwargs)
            except Exception as e:   # noqa: BLE001 - rethrown below
                last = e
                if attempt < attempts - 1:
                    _time.sleep(0.5 * (attempt + 1))
        load = os.getloadavg()[0] if hasattr(os, "getloadavg") else 0.0
        ncpu = os.cpu_count() or 1
        if load > ncpu:
            # imported only on the overloaded-box escape hatch: the
            # happy path (and a real failure on an idle box) must not
            # make pytest a runtime dependency of this shipped package
            import pytest

            pytest.xfail(
                f"load-flaky test failed {attempts}x under load "
                f"(loadavg {load:.1f} > {ncpu} cpus): {last!r}")
        raise last

    return wrapper
