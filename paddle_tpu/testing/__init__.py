"""paddle_tpu.testing — deterministic test harnesses.

Production robustness features need reproducible misbehavior to test
against; this package holds the harnesses that create it. Today:
:mod:`~paddle_tpu.testing.faults` — deterministic, site-named fault
injection at the serving-path seams (admission, prefill, chunked
prefill, decode segment, collect), driving the chaos suite
``tests/test_serving_faults.py`` and ``tools/serve_bench.py``'s
``--fault-rate`` chaos knobs.
"""
from .faults import SITES, FaultPlan, FaultyEngine, InjectedFault

__all__ = ["SITES", "FaultPlan", "FaultyEngine", "InjectedFault"]
