"""paddle.amp parity surface (reference: python/paddle/amp/__init__.py).

TPU-native AMP: bfloat16 is the default low-precision dtype (native MXU
input, fp32 exponent range → no loss scaling needed); the fp16 + dynamic
GradScaler path is kept for API/semantic parity.
"""
from . import debugging
from .amp_lists import BLACK_LIST, WHITE_LIST, black_list, white_list
from .auto_cast import amp_decorate, amp_guard, auto_cast, decorate
from .grad_scaler import AmpScaler, GradScaler, OptimizerState

__all__ = ["auto_cast", "decorate", "GradScaler", "AmpScaler", "amp_guard",
           "amp_decorate", "debugging", "white_list", "black_list",
           "is_float16_supported", "is_bfloat16_supported"]


def is_float16_supported(device=None) -> bool:
    return True


def is_bfloat16_supported(device=None) -> bool:
    return True
