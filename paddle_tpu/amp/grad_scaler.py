"""Dynamic loss scaling (reference: amp/grad_scaler.py:576 ``GradScaler``).

On TPU the default AMP dtype is bf16, whose exponent range matches fp32 —
scaling is then a no-op passthrough (enable=False). The full dynamic-scale
state machine is kept for fp16 parity: scale the loss, unscale grads before
step, skip the step on nan/inf, grow/shrink the scale.
"""
from __future__ import annotations

from enum import Enum
from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["GradScaler", "AmpScaler", "OptimizerState"]


class OptimizerState(Enum):
    INIT = 0
    UNSCALED = 1
    STEPPED = 2


class GradScaler:
    def __init__(self, enable: bool = True, init_loss_scaling: float = 2.0 ** 16,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 2000,
                 decr_every_n_nan_or_inf: int = 1,
                 use_dynamic_loss_scaling: bool = True):
        self._enable = bool(enable)
        self._init_loss_scaling = float(init_loss_scaling)
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._use_dynamic_loss_scaling = use_dynamic_loss_scaling
        self._incr_count = 0
        self._decr_count = 0
        self._found_inf = False
        self._opt_state = OptimizerState.INIT

    def is_enable(self) -> bool:
        return self._enable

    def is_use_dynamic_loss_scaling(self) -> bool:
        return self._use_dynamic_loss_scaling

    def scale(self, var):
        """Multiply the loss by the current scale (reference :627)."""
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        """Divide accumulated grads by the scale; detect nan/inf
        (reference GradScaler._unscale)."""
        if not self._enable or self._opt_state == OptimizerState.UNSCALED:
            return
        if self._opt_state == OptimizerState.STEPPED:
            raise RuntimeError(
                "unscale_() is being called after step(); call update() "
                "first (grads were already unscaled for this iteration)")
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p is None or p.grad is None:
                continue
            g = p.grad._value * inv
            if not bool(jnp.all(jnp.isfinite(g))):
                found = True
            p.grad = Tensor(g, stop_gradient=True)
        self._found_inf = found
        self._opt_state = OptimizerState.UNSCALED

    def step(self, optimizer):
        """unscale (if not already), skip the update on inf (reference :576)."""
        if not self._enable:
            optimizer.step()
            return
        if self._opt_state == OptimizerState.STEPPED:
            raise RuntimeError("step() has already been called since the "
                               "last update().")
        if self._opt_state != OptimizerState.UNSCALED:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._opt_state = OptimizerState.STEPPED

    def update(self):
        """Advance the dynamic-scale state machine."""
        if not self._enable:
            return
        if self._use_dynamic_loss_scaling:
            if self._found_inf:
                self._incr_count = 0
                self._decr_count += 1
                if self._decr_count >= self._decr_every_n_nan_or_inf:
                    self._scale = max(self._scale * self._decr_ratio, 1.0)
                    self._decr_count = 0
            else:
                self._decr_count = 0
                self._incr_count += 1
                if self._incr_count >= self._incr_every_n_steps:
                    self._scale *= self._incr_ratio
                    self._incr_count = 0
        self._found_inf = False
        self._opt_state = OptimizerState.INIT

    def minimize(self, optimizer, scaled_loss):
        """scaled.backward() must have been called; steps + updates."""
        self.step(optimizer)
        self.update()

    # -- scale accessors (reference :576 API) -------------------------------
    def get_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._init_loss_scaling = float(v)
        self._scale = float(v)

    def get_init_loss_scaling(self):
        return self._init_loss_scaling

    def set_incr_ratio(self, v):
        self._incr_ratio = v

    def get_incr_ratio(self):
        return self._incr_ratio

    def set_decr_ratio(self, v):
        self._decr_ratio = v

    def get_decr_ratio(self):
        return self._decr_ratio

    def set_incr_every_n_steps(self, v):
        self._incr_every_n_steps = v

    def get_incr_every_n_steps(self):
        return self._incr_every_n_steps

    def set_decr_every_n_nan_or_inf(self, v):
        self._decr_every_n_nan_or_inf = v

    def get_decr_every_n_nan_or_inf(self):
        return self._decr_every_n_nan_or_inf

    def state_dict(self) -> Dict[str, Any]:
        if not self._enable:
            return {}
        return {
            "scale": np.asarray(self._scale, np.float32),
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "incr_count": self._incr_count,
            "decr_count": self._decr_count,
            "use_dynamic_loss_scaling": self._use_dynamic_loss_scaling,
        }

    def load_state_dict(self, state: Dict[str, Any]):
        if not self._enable or not state:
            return
        self._scale = float(state["scale"])
        self._incr_ratio = state["incr_ratio"]
        self._decr_ratio = state["decr_ratio"]
        self._incr_every_n_steps = state["incr_every_n_steps"]
        self._decr_every_n_nan_or_inf = state["decr_every_n_nan_or_inf"]
        self._incr_count = state.get("incr_count", 0)
        self._decr_count = state.get("decr_count", 0)
        self._use_dynamic_loss_scaling = state.get(
            "use_dynamic_loss_scaling", True)


AmpScaler = GradScaler  # legacy alias
