"""AMP numerical debugging (reference: amp/debugging.py:83 TensorCheckerConfig,
:265 check_numerics; accuracy_compare.py).

Per-op tensor statistics collected through the apply_op sentry hook
(core/amp_state.checker) — the same choke point the reference instruments
with CheckTensorHasNanOrInf after every eager op.
"""
from __future__ import annotations

import contextlib
from enum import Enum
from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.amp_state import amp_state

__all__ = ["DebugMode", "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker", "check_numerics", "enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats"]


class DebugMode(Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3


class TensorCheckerConfig:
    """reference amp/debugging.py:83."""

    def __init__(self, enable: bool = False,
                 debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir: Optional[str] = None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = set(checked_op_list or [])
        self.skipped_op_list = set(skipped_op_list or [])
        self.debug_step = debug_step
        self.stack_height_limit = stack_height_limit
        self._found: List[str] = []

    def _check(self, op_name: str, leaves):
        if self.checked_op_list and op_name not in self.checked_op_list:
            return
        if op_name in self.skipped_op_list:
            return
        for o in leaves:
            n_nan = int(jnp.sum(jnp.isnan(o)))
            n_inf = int(jnp.sum(jnp.isinf(o)))
            if n_nan or n_inf:
                msg = (f"[nan_inf] op={op_name} shape={tuple(o.shape)} "
                       f"dtype={o.dtype} num_nan={n_nan} num_inf={n_inf}")
                self._found.append(msg)
                if self.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
                    raise RuntimeError(msg)
                print(msg)


_active_config: Optional[TensorCheckerConfig] = None


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    """reference amp/debugging.py — install the per-op checker."""
    global _active_config
    _active_config = checker_config
    if checker_config.enable:
        amp_state.checker = checker_config._check


def disable_tensor_checker():
    global _active_config
    _active_config = None
    amp_state.checker = None


def check_numerics(tensor, op_type: str = "", var_name: str = "",
                   debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT):
    """One-shot scan (reference amp/debugging.py:265): returns
    (num_nan, num_inf, num_zero) as arrays."""
    from ..core.tensor import Tensor

    v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    n_nan = jnp.sum(jnp.isnan(v))
    n_inf = jnp.sum(jnp.isinf(v))
    n_zero = jnp.sum(v == 0)
    if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT and (
            int(n_nan) or int(n_inf)):
        raise RuntimeError(
            f"check_numerics: {op_type}:{var_name} has nan={int(n_nan)} "
            f"inf={int(n_inf)}")
    return n_nan, n_inf, n_zero


# -- operator stats (reference enable_operator_stats_collection) ------------

_op_stats: Optional[Dict[str, Dict[str, int]]] = None
_prev_checker = None


def enable_operator_stats_collection():
    """Count per-op calls by output dtype (reference low_precision_op_list).
    Chains with (and restores) any checker installed by
    enable_tensor_checker."""
    global _op_stats, _prev_checker
    _op_stats = {}
    _prev_checker = amp_state.checker

    def _collect(op_name, leaves):
        for o in leaves:
            key = str(o.dtype)
            d = _op_stats.setdefault(op_name, {})
            d[key] = d.get(key, 0) + 1
        if _prev_checker is not None:
            _prev_checker(op_name, leaves)

    amp_state.checker = _collect


def disable_operator_stats_collection():
    global _op_stats, _prev_checker
    amp_state.checker = _prev_checker  # restore, don't uninstall, a live
    _prev_checker = None               # tensor checker
    stats, _op_stats = _op_stats, None
    if stats:
        print("<" + "-" * 20 + " op list " + "-" * 20 + ">")
        print(f"{'Op Name':<40} {'calls by dtype'}")
        for op, by_dtype in sorted(stats.items()):
            print(f"{op:<40} {by_dtype}")
    return stats


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()
