"""AMP op allow/deny lists (reference: python/paddle/amp/amp_lists.py).

Names are the ``op_name`` strings this framework's eager dispatcher emits
(apply_op op_name=...), the analog of the reference's fluid op types. On TPU
the low-precision dtype is bf16, whose dynamic range makes most fp16-black
ops safe — the black list keeps only the genuinely reduction/transcendental-
sensitive ones, matching the reference's bf16 lists rather than fp16.
"""
from __future__ import annotations

# ops that benefit from low precision (MXU-bound)
WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "linear", "conv2d", "conv1d", "conv3d",
    "conv2d_transpose", "einsum", "flash_attention", "sdpa",
    "fused_linear", "addmm",
}

# numerically sensitive — keep fp32
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "logsumexp",
    "softmax", "log_softmax", "cross_entropy", "softmax_with_cross_entropy",
    "c_softmax_with_cross_entropy", "nll_loss", "kl_div",
    "layer_norm", "batch_norm", "instance_norm", "group_norm", "rms_norm",
    "mean", "sum", "cumsum", "prod", "norm", "dist", "cosine_similarity",
    "erf", "erfinv", "pow", "rsqrt", "softplus", "square",
    "sigmoid_cross_entropy_with_logits", "binary_cross_entropy",
    "lm_loss_mean",
}

# everything else runs in whatever dtype its inputs already have ("gray")

FP16_WHITE_LIST = set(WHITE_LIST)
FP16_BLACK_LIST = set(BLACK_LIST)
BF16_WHITE_LIST = set(WHITE_LIST)
BF16_BLACK_LIST = set(BLACK_LIST)


def white_list(dtype="bfloat16"):
    return BF16_WHITE_LIST if "bf" in str(dtype) else FP16_WHITE_LIST


def black_list(dtype="bfloat16"):
    return BF16_BLACK_LIST if "bf" in str(dtype) else FP16_BLACK_LIST
