"""Autocast context + model decoration (reference: amp/auto_cast.py:646
``auto_cast``, :714 ``decorate``).

TPU-native policy: default low-precision dtype is **bfloat16** — no loss
scaling needed, the MXU consumes it natively. fp16 is supported for parity.
O1 casts white-listed op inputs; O2 additionally casts the model's params
once (master-weight pattern: the optimizer keeps fp32 moments, see
optimizer/functional.py).
"""
from __future__ import annotations

import contextlib
from typing import Optional, Sequence

import jax.numpy as jnp

from ..core.amp_state import amp_state
from ..core import dtype as dtypes
from . import amp_lists

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_decorate"]

_NORM_LAYERS = ("LayerNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
                "BatchNorm3D", "InstanceNorm1D", "InstanceNorm2D",
                "InstanceNorm3D", "GroupNorm", "SyncBatchNorm", "RMSNorm")


def _resolve_dtype(dtype):
    d = dtypes.convert_dtype(dtype or "bfloat16")
    if d not in (dtypes.float16, dtypes.bfloat16):
        raise ValueError(f"amp dtype must be float16/bfloat16, got {dtype}")
    return d


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list: Optional[Sequence] = None,
              custom_black_list: Optional[Sequence] = None, level: str = "O1",
              dtype: str = "bfloat16", use_promote: bool = True):
    """reference amp/auto_cast.py:646. Usable as context manager."""
    if level not in ("O0", "O1", "O2"):
        raise ValueError(f"level should be O0/O1/O2, got {level}")
    st = amp_state
    prev = (st.enabled, st.level, st.dtype, st.white, st.black)
    try:
        if not enable or level == "O0":
            # nested disable: an inner auto_cast(enable=False) must turn AMP
            # OFF for its scope even inside an enabled outer region
            st.enabled = False
            st.level = "O0"
        else:
            d = _resolve_dtype(dtype)
            white = set(amp_lists.white_list(d))
            black = set(amp_lists.black_list(d))
            if custom_white_list:
                white |= set(custom_white_list)
                black -= set(custom_white_list)
            if custom_black_list:
                black |= set(custom_black_list)
                white -= set(custom_black_list)
            st.enabled = True
            st.level = level
            st.dtype = jnp.dtype(d)
            st.white = white
            st.black = black
        yield
    finally:
        (st.enabled, st.level, st.dtype, st.white, st.black) = prev


amp_guard = auto_cast  # legacy alias (paddle.fluid.dygraph.amp_guard)


def decorate(models, optimizers=None, level: str = "O2", dtype: str = "bfloat16",
             master_weight=None, save_dtype=None,
             master_grad: bool = False, excluded_layers=None):
    """reference amp/auto_cast.py:714 — cast model params to the AMP dtype
    (norm layers stay fp32 for stability, as the reference keeps
    batch/layer norm in fp32 under O2)."""
    if level not in ("O1", "O2"):
        raise ValueError(f"level should be O1 or O2, got {level}")
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        d = _resolve_dtype(dtype)
        # excluded_layers accepts Layer classes AND instances (paddle API)
        ex = excluded_layers or ()
        if not isinstance(ex, (list, tuple)):
            ex = (ex,)
        ex_types = tuple(e for e in ex if isinstance(e, type))
        ex_ids = {id(e) for e in ex if not isinstance(e, type)}
        for m in model_list:
            _cast_model(m, d, ex_types, ex_ids)
            m._casted_by_pure_fp16 = True
    if optimizers is None:
        return model_list[0] if single else model_list
    return (model_list[0] if single else model_list), optimizers


amp_decorate = decorate


def _cast_model(layer, dtype, excluded_types=(), excluded_ids=frozenset()):
    name = type(layer).__name__
    keep = (name in _NORM_LAYERS
            or (excluded_types and isinstance(layer, excluded_types))
            or id(layer) in excluded_ids)
    if not keep:
        for pname, p in layer._parameters.items():
            if p is None:
                continue
            if jnp.issubdtype(p._value.dtype, jnp.floating):
                p.set_value(p._value.astype(dtype))
        for bname, b in layer._buffers.items():
            if b is None:
                continue
            if jnp.issubdtype(b._value.dtype, jnp.floating):
                b.set_value(b._value.astype(dtype))
    for sub in layer._sub_layers.values():
        _cast_model(sub, dtype, excluded_types, excluded_ids)
