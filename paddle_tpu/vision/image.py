"""Image IO backend registry (reference: python/paddle/vision/image.py —
pil/cv2 backend switch + image_load).
"""
from __future__ import annotations

import numpy as np

__all__ = ["set_image_backend", "get_image_backend", "image_load"]

_BACKEND = ["pil"]


def set_image_backend(backend: str):
    if backend not in ("pil", "cv2"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2'], but got {backend}")
    if backend == "cv2":
        try:
            import cv2  # noqa: F401
        except ImportError as e:
            raise ModuleNotFoundError(
                "cv2 backend requires opencv-python, which is not bundled "
                "in this image") from e
    _BACKEND[0] = backend


def get_image_backend() -> str:
    return _BACKEND[0]


def image_load(path: str, backend=None):
    """Load an image via the active backend (reference image.py
    image_load). Returns a PIL Image (pil) or ndarray (cv2)."""
    backend = backend or _BACKEND[0]
    if backend == "cv2":
        import cv2

        return cv2.imread(path)
    from PIL import Image

    return Image.open(path)
