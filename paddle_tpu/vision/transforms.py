"""paddle.vision.transforms parity (reference:
python/paddle/vision/transforms/transforms.py + functional.py).

Host-side numpy transforms (the input pipeline runs on CPU; the single
host→device transfer happens at the jit boundary). HWC uint8/float numpy in,
like the reference's 'backend=cv2' path; ToTensor produces CHW float."""
from __future__ import annotations

import numbers
import random
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "Pad", "BrightnessTransform", "Grayscale",
           "to_tensor", "normalize", "resize", "center_crop", "hflip",
           "vflip", "pad", "crop"]


def _np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._value)
    return np.asarray(img)


# -- functional ---------------------------------------------------------------


def to_tensor(img, data_format: str = "CHW") -> Tensor:
    arr = _np(img)
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    arr = arr.astype(np.float32)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


def normalize(img, mean, std, data_format: str = "CHW", to_rgb=False):
    arr = _np(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    return Tensor(out) if isinstance(img, Tensor) else out


def resize(img, size, interpolation: str = "bilinear"):
    """Nearest/bilinear resize via numpy (no cv2/PIL dependency)."""
    arr = _np(img)
    if isinstance(size, numbers.Number):
        h, w = arr.shape[:2]
        short = min(h, w)
        scale = size / short
        size = (int(round(h * scale)), int(round(w * scale)))
    oh, ow = size
    h, w = arr.shape[:2]
    if interpolation == "nearest":
        ys = np.clip((np.arange(oh) + 0.5) * h / oh, 0, h - 1).astype(int)
        xs = np.clip((np.arange(ow) + 0.5) * w / ow, 0, w - 1).astype(int)
        return arr[ys][:, xs]
    # bilinear
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = (ys - y0).reshape(-1, 1)
    wx = (xs - x0).reshape(1, -1)
    if arr.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    a = arr[y0][:, x0]
    b = arr[y0][:, x1]
    c = arr[y1][:, x0]
    d = arr[y1][:, x1]
    out = (a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx
           + c * wy * (1 - wx) + d * wy * wx)
    return out.astype(arr.dtype if arr.dtype != np.uint8 else np.float32)


def crop(img, top, left, height, width):
    return _np(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _np(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = arr.shape[:2]
    th, tw = output_size
    top = max((h - th) // 2, 0)
    left = max((w - tw) // 2, 0)
    return arr[top:top + th, left:left + tw]


def hflip(img):
    return _np(img)[:, ::-1].copy()


def vflip(img):
    return _np(img)[::-1].copy()


def pad(img, padding, fill=0, padding_mode: str = "constant"):
    arr = _np(img)
    if isinstance(padding, numbers.Number):
        padding = (padding,) * 4  # left, top, right, bottom
    if len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    l, t, r, b = padding
    width = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, width, mode=mode, **kw)


# -- transform classes --------------------------------------------------------


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = _np(img)
        if self.padding is not None:
            arr = pad(arr, self.padding, self.fill, self.padding_mode)
        h, w = arr.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            arr = pad(arr, (max(tw - w, 0), max(th - h, 0)), self.fill,
                      self.padding_mode)
            h, w = arr.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return arr[top:top + th, left:left + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return hflip(img)
        return _np(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return vflip(img)
        return _np(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        arr = _np(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _np(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        arr = _np(img).astype(np.float32) * factor
        return np.clip(arr, 0, 255 if arr.max() > 1 else 1.0)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels: int = 1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        arr = _np(img).astype(np.float32)
        gray = (0.299 * arr[..., 0] + 0.587 * arr[..., 1]
                + 0.114 * arr[..., 2])
        out = gray[..., None]
        if self.num_output_channels == 3:
            out = np.repeat(out, 3, axis=-1)
        return out
