"""paddle.vision.transforms parity (reference:
python/paddle/vision/transforms/transforms.py + functional.py).

Host-side numpy transforms (the input pipeline runs on CPU; the single
host→device transfer happens at the jit boundary). HWC uint8/float numpy in,
like the reference's 'backend=cv2' path; ToTensor produces CHW float."""
from __future__ import annotations

import math
import numbers
import random
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "Pad", "BrightnessTransform", "Grayscale",
           "to_tensor", "normalize", "resize", "center_crop", "hflip",
           "vflip", "pad", "crop"]


def _np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._value)
    return np.asarray(img)


# -- functional ---------------------------------------------------------------


def to_tensor(img, data_format: str = "CHW") -> Tensor:
    arr = _np(img)
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    arr = arr.astype(np.float32)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


def normalize(img, mean, std, data_format: str = "CHW", to_rgb=False):
    arr = _np(img).astype(np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    return Tensor(out) if isinstance(img, Tensor) else out


def resize(img, size, interpolation: str = "bilinear"):
    """Nearest/bilinear resize via numpy (no cv2/PIL dependency)."""
    arr = _np(img)
    if isinstance(size, numbers.Number):
        h, w = arr.shape[:2]
        short = min(h, w)
        scale = size / short
        size = (int(round(h * scale)), int(round(w * scale)))
    oh, ow = size
    h, w = arr.shape[:2]
    if interpolation == "nearest":
        ys = np.clip((np.arange(oh) + 0.5) * h / oh, 0, h - 1).astype(int)
        xs = np.clip((np.arange(ow) + 0.5) * w / ow, 0, w - 1).astype(int)
        return arr[ys][:, xs]
    # bilinear
    ys = (np.arange(oh) + 0.5) * h / oh - 0.5
    xs = (np.arange(ow) + 0.5) * w / ow - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = (ys - y0).reshape(-1, 1)
    wx = (xs - x0).reshape(1, -1)
    if arr.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    a = arr[y0][:, x0]
    b = arr[y0][:, x1]
    c = arr[y1][:, x0]
    d = arr[y1][:, x1]
    out = (a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx
           + c * wy * (1 - wx) + d * wy * wx)
    return out.astype(arr.dtype if arr.dtype != np.uint8 else np.float32)


def crop(img, top, left, height, width):
    return _np(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _np(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = arr.shape[:2]
    th, tw = output_size
    top = max((h - th) // 2, 0)
    left = max((w - tw) // 2, 0)
    return arr[top:top + th, left:left + tw]


def hflip(img):
    return _np(img)[:, ::-1].copy()


def vflip(img):
    return _np(img)[::-1].copy()


def pad(img, padding, fill=0, padding_mode: str = "constant"):
    arr = _np(img)
    if isinstance(padding, numbers.Number):
        padding = (padding,) * 4  # left, top, right, bottom
    if len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    l, t, r, b = padding
    width = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, width, mode=mode, **kw)


# -- transform classes --------------------------------------------------------


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = _np(img)
        if self.padding is not None:
            arr = pad(arr, self.padding, self.fill, self.padding_mode)
        h, w = arr.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            arr = pad(arr, (max(tw - w, 0), max(th - h, 0)), self.fill,
                      self.padding_mode)
            h, w = arr.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return arr[top:top + th, left:left + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return hflip(img)
        return _np(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob: float = 0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return vflip(img)
        return _np(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        arr = _np(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _np(img)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        arr = _np(img).astype(np.float32) * factor
        return np.clip(arr, 0, 255 if arr.max() > 1 else 1.0)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels: int = 1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        arr = _np(img).astype(np.float32)
        gray = (0.299 * arr[..., 0] + 0.587 * arr[..., 1]
                + 0.114 * arr[..., 2])
        out = gray[..., None]
        if self.num_output_channels == 3:
            out = np.repeat(out, 3, axis=-1)
        return out


# -- color / geometric functional tail (reference transforms/functional.py)


def to_grayscale(img, num_output_channels: int = 1):
    arr = _np(img).astype(np.float32)
    gray = (0.299 * arr[..., 0] + 0.587 * arr[..., 1] + 0.114 * arr[..., 2])
    out = gray[..., None]
    if num_output_channels == 3:
        out = np.repeat(out, 3, axis=-1)
    return out


def adjust_brightness(img, brightness_factor):
    arr = _np(img).astype(np.float32)
    hi = 255.0 if arr.max() > 1 else 1.0
    return np.clip(arr * brightness_factor, 0, hi)


def adjust_contrast(img, contrast_factor):
    arr = _np(img).astype(np.float32)
    hi = 255.0 if arr.max() > 1 else 1.0
    mean = to_grayscale(arr).mean()
    return np.clip(mean + contrast_factor * (arr - mean), 0, hi)


def adjust_saturation(img, saturation_factor):
    arr = _np(img).astype(np.float32)
    hi = 255.0 if arr.max() > 1 else 1.0
    gray = to_grayscale(arr, 3)
    return np.clip(gray + saturation_factor * (arr - gray), 0, hi)


def adjust_hue(img, hue_factor):
    """Rotate hue by hue_factor (in [-0.5, 0.5] turns; reference
    functional adjust_hue via HSV roundtrip)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = _np(img).astype(np.float32)
    hi = 255.0 if arr.max() > 1 else 1.0
    x = arr / hi
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx, mn = x.max(-1), x.min(-1)
    diff = mx - mn + 1e-8
    h = np.zeros_like(mx)
    mask = mx == r
    h[mask] = ((g - b) / diff % 6)[mask]
    mask = mx == g
    h[mask] = ((b - r) / diff + 2)[mask]
    mask = mx == b
    h[mask] = ((r - g) / diff + 4)[mask]
    h = (h / 6.0 + hue_factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-8), 0.0)
    v = mx
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p, q, t = v * (1 - s), v * (1 - s * f), v * (1 - s * (1 - f))
    i = i.astype(np.int32) % 6
    out = np.stack([
        np.choose(i, [v, q, p, p, t, v]),
        np.choose(i, [t, v, v, q, p, p]),
        np.choose(i, [p, p, t, v, v, q])], axis=-1)
    return np.clip(out * hi, 0, hi)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase the region [i:i+h, j:j+w] with value v (reference
    functional erase)."""
    arr = _np(img).astype(np.float32).copy()
    arr[..., i:i + h, j:j + w, :] = v
    return arr


def _affine_grid_sample(arr, matrix, fill=0.0):
    """Inverse-warp sampling with bilinear interpolation; matrix maps
    OUTPUT pixel coords -> input coords (3x3 row-major)."""
    hgt, wid = arr.shape[0], arr.shape[1]
    ys, xs = np.meshgrid(np.arange(hgt), np.arange(wid), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], axis=-1).astype(np.float32)
    src = coords @ np.asarray(matrix, np.float32).T
    sx, sy = src[..., 0], src[..., 1]
    x0 = np.floor(sx).astype(np.int32)
    y0 = np.floor(sy).astype(np.int32)
    fx, fy = sx - x0, sy - y0
    out = np.zeros_like(arr, dtype=np.float32)
    valid = (sx >= -1) & (sx <= wid) & (sy >= -1) & (sy <= hgt)
    for dy in (0, 1):
        for dx in (0, 1):
            xi = np.clip(x0 + dx, 0, wid - 1)
            yi = np.clip(y0 + dy, 0, hgt - 1)
            wgt = ((fx if dx else 1 - fx) * (fy if dy else 1 - fy))
            out += arr[yi, xi].astype(np.float32) * wgt[..., None]
    out[~valid] = fill
    return out


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="bilinear", fill=0, center=None):
    """Affine warp (reference functional affine): rotate/translate/scale/
    shear about the image center."""
    arr = _np(img).astype(np.float32)
    hgt, wid = arr.shape[0], arr.shape[1]
    cx, cy = center if center is not None else ((wid - 1) / 2,
                                                (hgt - 1) / 2)
    a = np.deg2rad(angle)
    sx, sy = (np.deg2rad(s) for s in
              (shear if isinstance(shear, (list, tuple)) else (shear, 0.0)))
    # forward = T(center+translate) @ R(angle) @ Shear @ Scale @ T(-center)
    # (torchvision/reference composition: shear is its own matrix, not an
    # angle offset inside the rotation)
    rot = np.asarray([[np.cos(a), -np.sin(a), 0],
                      [np.sin(a), np.cos(a), 0], [0, 0, 1]], np.float32)
    shear_m = np.asarray([[1, -np.tan(sx), 0], [-np.tan(sy), 1, 0],
                          [0, 0, 1]], np.float32)
    scale_m = np.asarray([[scale, 0, 0], [0, scale, 0], [0, 0, 1]],
                         np.float32)
    t1 = np.asarray([[1, 0, cx + translate[0]], [0, 1, cy + translate[1]],
                     [0, 0, 1]], np.float32)
    t0 = np.asarray([[1, 0, -cx], [0, 1, -cy], [0, 0, 1]], np.float32)
    fwd = t1 @ rot @ shear_m @ scale_m @ t0
    inv = np.linalg.inv(fwd)
    return _affine_grid_sample(arr, inv, fill=fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate counter-clockwise by angle degrees (reference functional
    rotate; expand unsupported keeps the input canvas)."""
    return affine(img, angle=angle, center=center, fill=fill)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective warp mapping startpoints -> endpoints (reference
    functional perspective; homography solved least-squares)."""
    arr = _np(img).astype(np.float32)
    A, b = [], []
    for (x, y), (u, v) in zip(startpoints, endpoints):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        b.append(u)
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
        b.append(v)
    h = np.linalg.lstsq(np.asarray(A, np.float32),
                        np.asarray(b, np.float32), rcond=None)[0]
    fwd = np.asarray([[h[0], h[1], h[2]], [h[3], h[4], h[5]],
                      [h[6], h[7], 1.0]], np.float32)
    inv = np.linalg.inv(fwd)

    hgt, wid = arr.shape[0], arr.shape[1]
    ys, xs = np.meshgrid(np.arange(hgt), np.arange(wid), indexing="ij")
    coords = np.stack([xs, ys, np.ones_like(xs)], -1).astype(np.float32)
    src = coords @ inv.T
    src = src[..., :2] / np.maximum(np.abs(src[..., 2:]), 1e-8) * np.sign(
        src[..., 2:])
    sx, sy = src[..., 0], src[..., 1]
    x0 = np.clip(np.round(sx).astype(np.int32), 0, wid - 1)
    y0 = np.clip(np.round(sy).astype(np.int32), 0, hgt - 1)
    out = arr[y0, x0]
    # half-pixel tolerance: exact-boundary coords carry float error
    invalid = ((sx < -0.5) | (sx > wid - 0.5)
               | (sy < -0.5) | (sy > hgt - 0.5))
    out[invalid] = fill
    return out


# -- random transform classes ----------------------------------------------


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _np(img)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _np(img)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _np(img)
        return adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """Randomly jitter brightness/contrast/saturation/hue (reference
    transforms.ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self.transforms[i]._apply_image(img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return rotate(img, angle, center=self.center, fill=self.fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        arr = _np(img)
        hgt, wid = arr.shape[0], arr.shape[1]
        angle = random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate:
            tx = random.uniform(-self.translate[0], self.translate[0]) * wid
            ty = random.uniform(-self.translate[1], self.translate[1]) * hgt
        sc = random.uniform(*self.scale) if self.scale else 1.0
        sh = random.uniform(-self.shear, self.shear) \
            if isinstance(self.shear, (int, float)) and self.shear else 0.0
        return affine(arr, angle=angle, translate=(tx, ty), scale=sc,
                      shear=(sh, 0.0), fill=self.fill, center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        if random.random() > self.prob:
            return _np(img)
        arr = _np(img)
        hgt, wid = arr.shape[0], arr.shape[1]
        d = self.distortion_scale

        def jitter(x, y):
            return (x + random.uniform(-d, d) * wid / 2,
                    y + random.uniform(-d, d) * hgt / 2)

        start = [(0, 0), (wid - 1, 0), (wid - 1, hgt - 1), (0, hgt - 1)]
        end = [jitter(*p) for p in start]
        return perspective(arr, start, end, fill=self.fill)


class RandomResizedCrop(BaseTransform):
    """Random area/aspect crop resized to size (reference
    transforms.RandomResizedCrop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _np(img)
        hgt, wid = arr.shape[0], arr.shape[1]
        area = hgt * wid
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = math.exp(random.uniform(math.log(self.ratio[0]),
                                         math.log(self.ratio[1])))
            w = int(round(math.sqrt(target * ar)))
            h = int(round(math.sqrt(target / ar)))
            if 0 < w <= wid and 0 < h <= hgt:
                top = random.randint(0, hgt - h)
                left = random.randint(0, wid - w)
                return resize(crop(arr, top, left, h, w), self.size,
                              self.interpolation)
        return resize(center_crop(arr, min(hgt, wid)), self.size,
                      self.interpolation)


class RandomErasing(BaseTransform):
    """Randomly erase a rectangle (reference transforms.RandomErasing)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if random.random() > self.prob:
            return _np(img)
        arr = _np(img)
        hgt, wid = arr.shape[0], arr.shape[1]
        area = hgt * wid
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            h = int(round(math.sqrt(target * ar)))
            w = int(round(math.sqrt(target / ar)))
            if h < hgt and w < wid:
                top = random.randint(0, hgt - h)
                left = random.randint(0, wid - w)
                return erase(arr, top, left, h, w, self.value)
        return arr


__all__ += ["ColorJitter", "ContrastTransform", "SaturationTransform",
            "HueTransform", "RandomRotation", "RandomAffine",
            "RandomPerspective", "RandomResizedCrop", "RandomErasing",
            "to_grayscale", "adjust_brightness", "adjust_contrast",
            "adjust_saturation", "adjust_hue", "affine", "rotate",
            "perspective", "erase"]
