"""paddle.vision parity (python/paddle/vision/__init__.py)."""
from . import datasets, models, ops, transforms  # noqa: F401
from .image import (get_image_backend, image_load,  # noqa: F401
                    set_image_backend)

__all__ = ["datasets", "models", "transforms", "ops",
           "get_image_backend", "set_image_backend", "image_load"]
