"""paddle.vision parity (python/paddle/vision/__init__.py)."""
from . import models  # noqa: F401
