"""paddle.vision parity (python/paddle/vision/__init__.py)."""
from . import datasets, models, transforms  # noqa: F401
