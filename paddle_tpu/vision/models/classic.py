"""Classic CNN families (reference: python/paddle/vision/models/ — lenet.py,
alexnet.py, vgg.py, mobilenetv1.py, mobilenetv2.py, squeezenet.py).
Constructor/API parity; NCHW layout like the reference (XLA transposes to
its preferred conv layout internally)."""
from __future__ import annotations

from ...nn import (AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D, Conv2D, Dropout,
                   Flatten, Layer, LayerList, Linear, MaxPool2D, ReLU,
                   ReLU6, Sequential)

__all__ = ["LeNet", "AlexNet", "alexnet", "VGG", "vgg11", "vgg13", "vgg16",
           "vgg19", "MobileNetV1", "mobilenet_v1", "MobileNetV2",
           "mobilenet_v2", "SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class LeNet(Layer):
    """reference vision/models/lenet.py."""

    def __init__(self, num_classes: int = 10):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1, padding=0), ReLU(),
            MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = Sequential(
                Linear(400, 120), Linear(120, 84), Linear(84, num_classes))

    def forward(self, inputs):
        x = self.features(inputs)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


class AlexNet(Layer):
    """reference vision/models/alexnet.py."""

    def __init__(self, num_classes: int = 1000, dropout: float = 0.5):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(),
            MaxPool2D(3, 2),
            Conv2D(64, 192, 5, padding=2), ReLU(),
            MaxPool2D(3, 2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(),
            MaxPool2D(3, 2))
        self.avgpool = AdaptiveAvgPool2D((6, 6))
        self.classifier = Sequential(
            Dropout(dropout), Linear(256 * 6 * 6, 4096), ReLU(),
            Dropout(dropout), Linear(4096, 4096), ReLU(),
            Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = self.avgpool(x)
        x = x.flatten(1)
        return self.classifier(x)


def alexnet(pretrained: bool = False, **kwargs):
    return AlexNet(**kwargs)


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(Layer):
    """reference vision/models/vgg.py."""

    def __init__(self, features, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(512 * 7 * 7, 4096), ReLU(), Dropout(),
                Linear(4096, 4096), ReLU(), Dropout(),
                Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def _make_vgg_layers(cfg, batch_norm: bool = False):
    layers = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(MaxPool2D(2, 2))
        else:
            layers.append(Conv2D(in_c, v, 3, padding=1))
            if batch_norm:
                layers.append(BatchNorm2D(v))
            layers.append(ReLU())
            in_c = v
    return Sequential(*layers)


def _vgg(cfg_key, batch_norm=False, **kw):
    return VGG(_make_vgg_layers(_VGG_CFGS[cfg_key], batch_norm), **kw)


def vgg11(pretrained=False, batch_norm=False, **kw):
    return _vgg("A", batch_norm, **kw)


def vgg13(pretrained=False, batch_norm=False, **kw):
    return _vgg("B", batch_norm, **kw)


def vgg16(pretrained=False, batch_norm=False, **kw):
    return _vgg("D", batch_norm, **kw)


def vgg19(pretrained=False, batch_norm=False, **kw):
    return _vgg("E", batch_norm, **kw)


class _ConvBNReLU(Sequential):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0, groups=1,
                 relu6=False):
        super().__init__(
            Conv2D(in_c, out_c, kernel, stride=stride, padding=padding,
                   groups=groups, bias_attr=False),
            BatchNorm2D(out_c),
            ReLU6() if relu6 else ReLU())


class _DepthwiseSeparable(Layer):
    def __init__(self, in_c, out_c1, out_c2, stride):
        super().__init__()
        self.dw = _ConvBNReLU(in_c, out_c1, 3, stride=stride, padding=1,
                              groups=in_c)
        self.pw = _ConvBNReLU(out_c1, out_c2, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(Layer):
    """reference vision/models/mobilenetv1.py."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: max(int(c * scale), 8)
        cfg = [(s(32), s(64), 1), (s(64), s(128), 2), (s(128), s(128), 1),
               (s(128), s(256), 2), (s(256), s(256), 1),
               (s(256), s(512), 2)] + [(s(512), s(512), 1)] * 5 + [
                  (s(512), s(1024), 2), (s(1024), s(1024), 1)]
        blocks = [_ConvBNReLU(3, s(32), 3, stride=2, padding=1)]
        for in_c, out_c, st in cfg:
            blocks.append(_DepthwiseSeparable(in_c, in_c, out_c, st))
        self.features = Sequential(*blocks)
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)


class _InvertedResidual(Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(in_c, hidden, 1, relu6=True))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride=stride, padding=1,
                        groups=hidden, relu6=True),
            Conv2D(hidden, out_c, 1, bias_attr=False),
            BatchNorm2D(out_c),
        ]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    """reference vision/models/mobilenetv2.py."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = max(int(32 * scale), 8)
        feats = [_ConvBNReLU(3, in_c, 3, stride=2, padding=1, relu6=True)]
        for t, c, n, s in cfg:
            out_c = max(int(c * scale), 8)
            for i in range(n):
                feats.append(_InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        last = max(int(1280 * scale), 1280 if scale <= 1.0 else 8)
        feats.append(_ConvBNReLU(in_c, last, 1, relu6=True))
        self.features = Sequential(*feats)
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Sequential(Dropout(0.2),
                                         Linear(last, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kw):
    return MobileNetV2(scale=scale, **kw)


class _Fire(Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = Sequential(Conv2D(in_c, squeeze, 1), ReLU())
        self.expand1 = Sequential(Conv2D(squeeze, e1, 1), ReLU())
        self.expand3 = Sequential(Conv2D(squeeze, e3, 3, padding=1), ReLU())

    def forward(self, x):
        import paddle_tpu as _p

        x = self.squeeze(x)
        return _p.concat([self.expand1(x), self.expand3(x)], axis=1)


class SqueezeNet(Layer):
    """reference vision/models/squeezenet.py."""

    def __init__(self, version: str = "1.0", num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, stride=2), ReLU(), MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:
            self.features = Sequential(
                Conv2D(3, 64, 3, stride=2), ReLU(), MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                MaxPool2D(3, 2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = Sequential(
            Dropout(0.5), Conv2D(512, num_classes, 1), ReLU())
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        return x.flatten(1)


def squeezenet1_0(pretrained=False, **kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(pretrained=False, **kw):
    return SqueezeNet("1.1", **kw)
