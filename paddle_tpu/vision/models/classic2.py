"""Second classic CNN batch (reference: python/paddle/vision/models/ —
densenet.py, googlenet.py, inceptionv3.py, mobilenetv3.py,
shufflenetv2.py). Constructor/API parity, NCHW."""
from __future__ import annotations

import math

import jax.numpy as jnp

from ...nn import (AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D, Conv2D,
                   Dropout, Flatten, Hardsigmoid, Hardswish, Layer, Linear,
                   MaxPool2D, ReLU, Sequential, Swish)
from ...ops import concat, flatten, reshape, transpose

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264", "GoogLeNet", "googlenet",
           "InceptionV3", "inception_v3", "MobileNetV3Large",
           "MobileNetV3Small", "mobilenet_v3_large", "mobilenet_v3_small",
           "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]


def _cbr(in_c, out_c, k, s=1, p=0, groups=1, act="relu"):
    layers = [Conv2D(in_c, out_c, k, stride=s, padding=p, groups=groups,
                     bias_attr=False), BatchNorm2D(out_c)]
    if act == "relu":
        layers.append(ReLU())
    elif act == "hardswish":
        layers.append(Hardswish())
    elif act == "swish":
        layers.append(Swish())
    # act == "none": conv+bn only
    return Sequential(*layers)


# ---------------------------------------------------------------------------
# DenseNet
# ---------------------------------------------------------------------------


class _DenseLayer(Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = BatchNorm2D(in_c)
        self.relu = ReLU()
        self.conv1 = Conv2D(in_c, bn_size * growth_rate, 1,
                            bias_attr=False)
        self.bn2 = BatchNorm2D(bn_size * growth_rate)
        self.conv2 = Conv2D(bn_size * growth_rate, growth_rate, 3,
                            padding=1, bias_attr=False)
        self.dropout = Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class _Transition(Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = BatchNorm2D(in_c)
        self.relu = ReLU()
        self.conv = Conv2D(in_c, out_c, 1, bias_attr=False)
        self.pool = AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


_DENSE_CFG = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
              169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
              264: (6, 12, 64, 48)}


class DenseNet(Layer):
    """reference vision/models/densenet.py DenseNet."""

    def __init__(self, layers: int = 121, bn_size: int = 4, dropout=0.0,
                 num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        block_cfg = _DENSE_CFG[layers]
        growth = 48 if layers == 161 else 32
        init_c = 96 if layers == 161 else 64
        self.features = [Sequential(
            Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
            BatchNorm2D(init_c), ReLU(), MaxPool2D(3, stride=2, padding=1))]
        c = init_c
        for bi, n in enumerate(block_cfg):
            for _ in range(n):
                self.features.append(_DenseLayer(c, growth, bn_size,
                                                 dropout))
                c += growth
            if bi != len(block_cfg) - 1:
                self.features.append(_Transition(c, c // 2))
                c //= 2
        self.features = Sequential(*self.features)
        self.bn_last = BatchNorm2D(c)
        self.relu = ReLU()
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Linear(c, num_classes)

    def forward(self, x):
        x = self.relu(self.bn_last(self.features(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def _densenet(layers, pretrained=False, **kw):
    return DenseNet(layers=layers, **kw)


def densenet121(pretrained=False, **kw):
    return _densenet(121, pretrained, **kw)


def densenet161(pretrained=False, **kw):
    return _densenet(161, pretrained, **kw)


def densenet169(pretrained=False, **kw):
    return _densenet(169, pretrained, **kw)


def densenet201(pretrained=False, **kw):
    return _densenet(201, pretrained, **kw)


def densenet264(pretrained=False, **kw):
    return _densenet(264, pretrained, **kw)


# ---------------------------------------------------------------------------
# GoogLeNet (Inception v1)
# ---------------------------------------------------------------------------


class _Inception(Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _cbr(in_c, c1, 1)
        self.b2 = Sequential(_cbr(in_c, c3r, 1), _cbr(c3r, c3, 3, p=1))
        self.b3 = Sequential(_cbr(in_c, c5r, 1), _cbr(c5r, c5, 5, p=2))
        self.b4 = Sequential(MaxPool2D(3, stride=1, padding=1),
                             _cbr(in_c, proj, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class GoogLeNet(Layer):
    """reference vision/models/googlenet.py (returns (out, aux1, aux2) in
    train mode like the reference)."""

    def __init__(self, num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            _cbr(3, 64, 7, s=2, p=3), MaxPool2D(3, stride=2, padding=1),
            _cbr(64, 64, 1), _cbr(64, 192, 3, p=1),
            MaxPool2D(3, stride=2, padding=1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = MaxPool2D(3, stride=2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = MaxPool2D(3, stride=2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = Dropout(0.2)
            self.fc = Linear(1024, num_classes)
            self.aux1 = Sequential(AdaptiveAvgPool2D(4),
                                   _cbr(512, 128, 1), Flatten(),
                                   Linear(2048, 1024), ReLU(),
                                   Dropout(0.7), Linear(1024, num_classes))
            self.aux2 = Sequential(AdaptiveAvgPool2D(4),
                                   _cbr(528, 128, 1), Flatten(),
                                   Linear(2048, 1024), ReLU(),
                                   Dropout(0.7), Linear(1024, num_classes))

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4a(x)
        aux1 = self.aux1(x) if (self.training and self.num_classes > 0) \
            else None
        x = self.i4d(self.i4c(self.i4b(x)))
        aux2 = self.aux2(x) if (self.training and self.num_classes > 0) \
            else None
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        if self.training and self.num_classes > 0:
            return x, aux1, aux2
        return x


def googlenet(pretrained=False, **kw):
    return GoogLeNet(**kw)


# ---------------------------------------------------------------------------
# InceptionV3
# ---------------------------------------------------------------------------


class _IncA(Layer):
    def __init__(self, in_c, pool_c):
        super().__init__()
        self.b1 = _cbr(in_c, 64, 1)
        self.b5 = Sequential(_cbr(in_c, 48, 1), _cbr(48, 64, 5, p=2))
        self.b3 = Sequential(_cbr(in_c, 64, 1), _cbr(64, 96, 3, p=1),
                             _cbr(96, 96, 3, p=1))
        self.bp = Sequential(AvgPool2D(3, stride=1, padding=1),
                             _cbr(in_c, pool_c, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], 1)


class _IncB(Layer):  # grid reduction
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _cbr(in_c, 384, 3, s=2)
        self.b33 = Sequential(_cbr(in_c, 64, 1), _cbr(64, 96, 3, p=1),
                              _cbr(96, 96, 3, s=2))
        self.pool = MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b33(x), self.pool(x)], 1)


class _IncC(Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _cbr(in_c, 192, 1)
        self.b7 = Sequential(_cbr(in_c, c7, 1),
                             _cbr(c7, c7, (1, 7), p=(0, 3)),
                             _cbr(c7, 192, (7, 1), p=(3, 0)))
        self.b77 = Sequential(_cbr(in_c, c7, 1),
                              _cbr(c7, c7, (7, 1), p=(3, 0)),
                              _cbr(c7, c7, (1, 7), p=(0, 3)),
                              _cbr(c7, c7, (7, 1), p=(3, 0)),
                              _cbr(c7, 192, (1, 7), p=(0, 3)))
        self.bp = Sequential(AvgPool2D(3, stride=1, padding=1),
                             _cbr(in_c, 192, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b77(x), self.bp(x)], 1)


class _IncD(Layer):  # grid reduction 17->8
    def __init__(self, in_c):
        super().__init__()
        self.b3 = Sequential(_cbr(in_c, 192, 1), _cbr(192, 320, 3, s=2))
        self.b7 = Sequential(_cbr(in_c, 192, 1),
                             _cbr(192, 192, (1, 7), p=(0, 3)),
                             _cbr(192, 192, (7, 1), p=(3, 0)),
                             _cbr(192, 192, 3, s=2))
        self.pool = MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b7(x), self.pool(x)], 1)


class _IncE(Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _cbr(in_c, 320, 1)
        self.b3_stem = _cbr(in_c, 384, 1)
        self.b3_a = _cbr(384, 384, (1, 3), p=(0, 1))
        self.b3_b = _cbr(384, 384, (3, 1), p=(1, 0))
        self.b33_stem = Sequential(_cbr(in_c, 448, 1),
                                   _cbr(448, 384, 3, p=1))
        self.b33_a = _cbr(384, 384, (1, 3), p=(0, 1))
        self.b33_b = _cbr(384, 384, (3, 1), p=(1, 0))
        self.bp = Sequential(AvgPool2D(3, stride=1, padding=1),
                             _cbr(in_c, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        t = self.b33_stem(x)
        return concat([self.b1(x), self.b3_a(s), self.b3_b(s),
                       self.b33_a(t), self.b33_b(t), self.bp(x)], 1)


class InceptionV3(Layer):
    """reference vision/models/inceptionv3.py."""

    def __init__(self, num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            _cbr(3, 32, 3, s=2), _cbr(32, 32, 3), _cbr(32, 64, 3, p=1),
            MaxPool2D(3, stride=2), _cbr(64, 80, 1), _cbr(80, 192, 3),
            MaxPool2D(3, stride=2))
        self.blocks = Sequential(
            _IncA(192, 32), _IncA(256, 64), _IncA(288, 64),
            _IncB(288),
            _IncC(768, 128), _IncC(768, 160), _IncC(768, 160),
            _IncC(768, 192),
            _IncD(768),
            _IncE(1280), _IncE(2048))
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = Dropout(0.5)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kw):
    return InceptionV3(**kw)


# ---------------------------------------------------------------------------
# MobileNetV3
# ---------------------------------------------------------------------------


class _SE(Layer):
    def __init__(self, c, r=4):
        super().__init__()
        self.pool = AdaptiveAvgPool2D(1)
        self.fc1 = Conv2D(c, c // r, 1)
        self.relu = ReLU()
        self.fc2 = Conv2D(c // r, c, 1)
        self.hs = Hardsigmoid()

    def forward(self, x):
        s = self.hs(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _MBV3Block(Layer):
    def __init__(self, in_c, exp, out_c, k, s, se, act):
        super().__init__()
        self.use_res = s == 1 and in_c == out_c
        layers = []
        if exp != in_c:
            layers.append(_cbr(in_c, exp, 1, act=act))
        layers.append(_cbr(exp, exp, k, s=s, p=k // 2, groups=exp, act=act))
        if se:
            layers.append(_SE(exp))
        layers += [Conv2D(exp, out_c, 1, bias_attr=False),
                   BatchNorm2D(out_c)]
        self.block = Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_LARGE = [
    # k, exp, out, se, act, stride
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1)]

_V3_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1)]


def _mk(v, scale):
    out = int(v * scale)
    return max(out + (8 - out % 8) % 8, 8) if out % 8 else max(out, 8)


class _MobileNetV3(Layer):
    def __init__(self, cfg, last_exp, num_classes=1000, scale=1.0,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        c = _mk(16, scale)
        layers = [_cbr(3, c, 3, s=2, p=1, act="hardswish")]
        for k, exp, out, se, act, s in cfg:
            layers.append(_MBV3Block(c, _mk(exp, scale), _mk(out, scale),
                                     k, s, se, act))
            c = _mk(out, scale)
        last_c = _mk(last_exp, scale)
        layers.append(_cbr(c, last_c, 1, act="hardswish"))
        self.features = Sequential(*layers)
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            hid = 1280 if last_exp == 960 else 1024
            self.classifier = Sequential(
                Linear(last_c, hid), Hardswish(), Dropout(0.2),
                Linear(hid, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


class MobileNetV3Large(_MobileNetV3):
    """reference vision/models/mobilenetv3.py MobileNetV3Large."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 960, num_classes, scale, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 576, num_classes, scale, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kw):
    return MobileNetV3Large(scale=scale, **kw)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    return MobileNetV3Small(scale=scale, **kw)


# ---------------------------------------------------------------------------
# ShuffleNetV2
# ---------------------------------------------------------------------------


def _channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = reshape(x, [n, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [n, c, h, w])


class _ShuffleUnit(Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            self.branch2 = Sequential(
                _cbr(in_c // 2, branch_c, 1, act=act),
                _cbr(branch_c, branch_c, 3, s=1, p=1, groups=branch_c,
                     act="none"),
                _cbr(branch_c, branch_c, 1, act=act))
        else:
            self.branch1 = Sequential(
                _cbr(in_c, in_c, 3, s=stride, p=1, groups=in_c, act="none"),
                _cbr(in_c, branch_c, 1, act=act))
            self.branch2 = Sequential(
                _cbr(in_c, branch_c, 1, act=act),
                _cbr(branch_c, branch_c, 3, s=stride, p=1, groups=branch_c,
                     act="none"),
                _cbr(branch_c, branch_c, 1, act=act))

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1 = x[:, :c]
            x2 = x[:, c:]
            out = concat([x1, self.branch2(x2)], 1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], 1)
        return _channel_shuffle(out, 2)


_SHUFFLE_CFG = {0.25: (24, (24, 48, 96), 512),
                0.33: (24, (32, 64, 128), 512),
                0.5: (24, (48, 96, 192), 1024),
                1.0: (24, (116, 232, 464), 1024),
                1.5: (24, (176, 352, 704), 1024),
                2.0: (24, (244, 488, 976), 2048)}


class ShuffleNetV2(Layer):
    """reference vision/models/shufflenetv2.py."""

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stem_c, stage_cs, last_c = _SHUFFLE_CFG[scale]
        self.stem = Sequential(_cbr(3, stem_c, 3, s=2, p=1, act=act),
                               MaxPool2D(3, stride=2, padding=1))
        blocks = []
        c = stem_c
        for sc in stage_cs:
            blocks.append(_ShuffleUnit(c, sc, 2, act))
            for _ in range(3 if sc == stage_cs[0] else
                           (7 if sc == stage_cs[1] else 3)):
                blocks.append(_ShuffleUnit(sc, sc, 1, act))
            c = sc
        self.blocks = Sequential(*blocks)
        self.last = _cbr(c, last_c, 1, act=act)
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = Linear(last_c, num_classes)

    def forward(self, x):
        x = self.last(self.blocks(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return ShuffleNetV2(scale=0.25, **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return ShuffleNetV2(scale=0.33, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2(scale=0.5, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2(scale=1.0, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return ShuffleNetV2(scale=1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2(scale=2.0, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    return ShuffleNetV2(scale=1.0, act="swish", **kw)
