"""paddle.vision.models parity (LeNet/VGG/MobileNet land with the vision widening)."""
from .resnet import *  # noqa: F401,F403
