"""paddle.vision.models parity (reference: python/paddle/vision/models/)."""
from .classic import (AlexNet, LeNet, MobileNetV1, MobileNetV2, SqueezeNet,
                      VGG, alexnet, mobilenet_v1, mobilenet_v2,
                      squeezenet1_0, squeezenet1_1, vgg11, vgg13, vgg16,
                      vgg19)
from .resnet import *  # noqa: F401,F403
