"""paddle.vision.ops parity — the detection operator set (reference:
python/paddle/vision/ops.py).

TPU-native notes: RoI pooling/alignment are expressed as dense gather +
bilinear interpolation (static shapes, MXU-friendly batched einsums);
NMS-family ops are host-side numpy like the reference's CPU kernels —
selection with data-dependent output sizes belongs off-device; deformable
conv composes the offset-gather with a dense conv.
"""
from __future__ import annotations

import math
import numpy as np

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ..nn import Layer
from ..ops._helpers import unwrap

__all__ = ["yolo_loss", "yolo_box", "prior_box", "box_coder",
           "deform_conv2d", "DeformConv2D", "distribute_fpn_proposals",
           "generate_proposals", "read_file", "decode_jpeg", "psroi_pool",
           "PSRoIPool", "roi_pool", "RoIPool", "roi_align", "RoIAlign",
           "nms", "matrix_nms"]


# ---------------------------------------------------------------------------
# RoI family
# ---------------------------------------------------------------------------


def _roi_to_batch(bv, bn):
    """Image index for each RoI from per-image counts (shared by the RoI
    family)."""
    starts = jnp.cumsum(bn) - bn
    return jnp.sum((jnp.arange(bv.shape[0])[:, None]
                    >= starts[None, :]).astype(jnp.int32), axis=1) - 1


def _bilinear_gather(img, y, x):
    """Bilinear sample img [C, H, W] at fractional (y, x) arrays (shared
    by roi_align and deform_conv2d)."""
    H, W = img.shape[1], img.shape[2]
    y = jnp.clip(y, 0.0, H - 1.0)
    x = jnp.clip(x, 0.0, W - 1.0)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    fy, fx = y - y0, x - x0
    return (img[:, y0, x0] * (1 - fy) * (1 - fx)
            + img[:, y0, x1] * (1 - fy) * fx
            + img[:, y1, x0] * fy * (1 - fx)
            + img[:, y1, x1] * fy * fx)


def _roi_align_one(feat, box, out_h, out_w, spatial_scale, sampling_ratio,
                   aligned):
    """feat [C, H, W]; box [4] (x1, y1, x2, y2) in input coords."""
    off = 0.5 if aligned else 0.0
    x1 = box[0] * spatial_scale - off
    y1 = box[1] * spatial_scale - off
    x2 = box[2] * spatial_scale - off
    y2 = box[3] * spatial_scale - off
    rw = jnp.maximum(x2 - x1, 1e-4 if aligned else 1.0)
    rh = jnp.maximum(y2 - y1, 1e-4 if aligned else 1.0)
    bin_h = rh / out_h
    bin_w = rw / out_w
    ratio = sampling_ratio if sampling_ratio > 0 else 2
    # sample grid: [out_h, ratio] x [out_w, ratio]
    iy = (jnp.arange(out_h)[:, None] * bin_h + y1
          + (jnp.arange(ratio)[None, :] + 0.5) * bin_h / ratio)
    ix = (jnp.arange(out_w)[:, None] * bin_w + x1
          + (jnp.arange(ratio)[None, :] + 0.5) * bin_w / ratio)
    H, W = feat.shape[1], feat.shape[2]

    bilinear = lambda y, x: _bilinear_gather(feat, y, x)

    # all sample points at once: [out_h*ratio] x [out_w*ratio]
    ys = iy.reshape(-1)
    xs = ix.reshape(-1)
    grid_y, grid_x = jnp.meshgrid(ys, xs, indexing="ij")
    vals = bilinear(grid_y.reshape(-1), grid_x.reshape(-1))  # [C, P]
    C = feat.shape[0]
    vals = vals.reshape(C, out_h, ratio, out_w, ratio)
    return vals.mean(axis=(2, 4))                            # [C, oh, ow]


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference vision/ops.py roi_align / phi roi_align
    kernel). x [N, C, H, W]; boxes [R, 4]; boxes_num [N]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(xv, bv, bn):
        # map each roi to its batch image via boxes_num prefix sums
        roi_batch = _roi_to_batch(bv, bn)

        def one(box, bidx):
            return _roi_align_one(xv[bidx], box, oh, ow, spatial_scale,
                                  sampling_ratio, aligned)

        return jax.vmap(one)(bv, roi_batch)

    return apply_op(f, x, boxes, boxes_num, op_name="roi_align")


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool — max over quantized bins (reference roi_pool)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(xv, bv, bn):
        H, W = xv.shape[2], xv.shape[3]
        roi_batch = _roi_to_batch(bv, bn)

        def one(box, bidx):
            feat = xv[bidx]
            x1 = jnp.round(box[0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(box[1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.round(box[2] * spatial_scale).astype(jnp.int32)
            y2 = jnp.round(box[3] * spatial_scale).astype(jnp.int32)
            rh = jnp.maximum(y2 - y1 + 1, 1)
            rw = jnp.maximum(x2 - x1 + 1, 1)
            # dense mask-based max per bin (static shapes for jit)
            ys = jnp.arange(H)
            xs = jnp.arange(W)
            bin_y = jnp.clip(((ys - y1) * oh) // rh, 0, oh - 1)
            bin_x = jnp.clip(((xs - x1) * ow) // rw, 0, ow - 1)
            in_y = (ys >= y1) & (ys <= y2)
            in_x = (xs >= x1) & (xs <= x2)
            onehot_y = (bin_y[:, None] == jnp.arange(oh)[None, :]) \
                & in_y[:, None]                           # [H, oh]
            onehot_x = (bin_x[:, None] == jnp.arange(ow)[None, :]) \
                & in_x[:, None]                           # [W, ow]
            # [C,H,W] -> [C,oh,ow] via masked max over H then W
            tmp = jnp.where(onehot_y[None, :, :, None],
                            feat[:, :, None, :], -jnp.inf).max(axis=1)
            out = jnp.where(onehot_x[None, None, :, :],
                            tmp[:, :, :, None], -jnp.inf).max(axis=2)
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return jax.vmap(one)(bv, roi_batch)

    return apply_op(f, x, boxes, boxes_num, op_name="roi_pool")


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference psroi_pool): input
    channels C = out_c * oh * ow; bin (i, j) averages its OWN channel
    group within the spatial bin."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(xv, bv, bn):
        N, C, H, W = xv.shape
        out_c = C // (oh * ow)
        roi_batch = _roi_to_batch(bv, bn)

        def one(box, bidx):
            feat = xv[bidx].reshape(out_c, oh, ow, H, W)
            x1 = box[0] * spatial_scale
            y1 = box[1] * spatial_scale
            rw = jnp.maximum((box[2] - box[0]) * spatial_scale, 0.1)
            rh = jnp.maximum((box[3] - box[1]) * spatial_scale, 0.1)
            bh, bw = rh / oh, rw / ow
            ys = jnp.arange(H, dtype=jnp.float32)
            xs = jnp.arange(W, dtype=jnp.float32)
            # bin masks per (i, j)
            iy = jnp.clip(jnp.floor((ys - y1) / bh), 0, oh - 1)
            ix = jnp.clip(jnp.floor((xs - x1) / bw), 0, ow - 1)
            my = ((iy[:, None] == jnp.arange(oh)[None, :])
                  & (ys[:, None] >= y1) & (ys[:, None] <= y1 + rh))
            mx = ((ix[:, None] == jnp.arange(ow)[None, :])
                  & (xs[:, None] >= x1) & (xs[:, None] <= x1 + rw))
            mask = my.T[:, None, :, None] * mx.T[None, :, None, :]
            # [oh, ow, H, W]; select diag channel groups
            num = jnp.einsum("cijhw,ijhw->cij", feat, mask.astype(
                feat.dtype))
            den = jnp.maximum(mask.sum((-1, -2)), 1.0)
            return num / den[None]

        return jax.vmap(one)(bv, roi_batch)

    return apply_op(f, x, boxes, boxes_num, op_name="psroi_pool")


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


# ---------------------------------------------------------------------------
# NMS family (host-side numpy — data-dependent output length)
# ---------------------------------------------------------------------------


def _iou_matrix(boxes):
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    xx1 = np.maximum(x1[:, None], x1[None, :])
    yy1 = np.maximum(y1[:, None], y1[None, :])
    xx2 = np.minimum(x2[:, None], x2[None, :])
    yy2 = np.minimum(y2[:, None], y2[None, :])
    inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / np.maximum(union, 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Hard NMS (reference vision/ops.py nms). Returns kept indices,
    score-ordered."""
    b = np.asarray(unwrap(boxes), np.float32)
    s = np.arange(len(b))[::-1].astype(np.float32) if scores is None \
        else np.asarray(unwrap(scores), np.float32)
    cats = None if category_idxs is None else np.asarray(
        unwrap(category_idxs))
    order = np.argsort(-s)
    iou = _iou_matrix(b)
    keep = []
    suppressed = np.zeros(len(b), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        over = iou[i] > iou_threshold
        if cats is not None:
            over = over & (cats == cats[i])
        suppressed |= over
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference matrix_nms; SOLOv2): decay every box's score
    by its worst overlap with a higher-scored same-class box."""
    b = np.asarray(unwrap(bboxes), np.float32)
    sc = np.asarray(unwrap(scores), np.float32)
    N = b.shape[0]
    outs, idxs, nums = [], [], []
    for n in range(N):
        per_img = []
        per_idx = []
        for c in range(sc.shape[1]):
            if c == background_label:
                continue
            s = sc[n, c]
            sel = np.nonzero(s > score_threshold)[0]
            if sel.size == 0:
                continue
            order = sel[np.argsort(-s[sel])][:nms_top_k]
            boxes_c = b[n, order]
            scores_c = s[order]
            iou = _iou_matrix(boxes_c)
            iou = np.triu(iou, k=1)
            max_iou = iou.max(axis=0, initial=0.0)
            # decay_j = min_i f(iou_ij) / f(max_iou_i): compensation is by
            # the SUPPRESSING box i's own worst overlap (SOLOv2 eq. 4)
            if use_gaussian:
                decay = np.exp(-(iou ** 2 - max_iou[:, None] ** 2)
                               / gaussian_sigma).min(axis=0, initial=1.0)
            else:
                decay = ((1 - iou) / np.maximum(1 - max_iou[:, None],
                                                1e-10)).min(axis=0,
                                                            initial=1.0)
            dec_scores = scores_c * decay
            ok = dec_scores > post_threshold
            for i, flag in enumerate(ok):
                if flag:
                    per_img.append([c, dec_scores[i], *boxes_c[i]])
                    per_idx.append(order[i])
        per_img.sort(key=lambda r: -r[1])
        per_img = per_img[:keep_top_k]
        per_idx = per_idx[:keep_top_k]
        nums.append(len(per_img))
        outs.extend(per_img)
        idxs.extend(per_idx)
    out = Tensor(jnp.asarray(np.asarray(outs, np.float32).reshape(-1, 6)))
    res = [out]
    if return_index:
        res.append(Tensor(jnp.asarray(np.asarray(idxs, np.int64))))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(nums, np.int32))))
    return tuple(res) if len(res) > 1 else out


# ---------------------------------------------------------------------------
# Anchors / box coding / YOLO
# ---------------------------------------------------------------------------


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) boxes (reference prior_box)."""
    iv = unwrap(input)
    imv = unwrap(image)
    H, W = iv.shape[2], iv.shape[3]
    img_h, img_w = imv.shape[2], imv.shape[3]
    step_w = steps[0] or img_w / W
    step_h = steps[1] or img_h / H
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    sizes = []
    for k, ms in enumerate(min_sizes):
        for ar in ars:
            sizes.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
        if max_sizes:
            bs = math.sqrt(ms * max_sizes[k])
            sizes.append((bs, bs))
    num_priors = len(sizes)
    cx = (np.arange(W) + offset) * step_w
    cy = (np.arange(H) + offset) * step_h
    boxes = np.zeros((H, W, num_priors, 4), np.float32)
    for p, (bw, bh) in enumerate(sizes):
        boxes[:, :, p, 0] = (cx[None, :] - bw / 2) / img_w
        boxes[:, :, p, 1] = (cy[:, None] - bh / 2) / img_h
        boxes[:, :, p, 2] = (cx[None, :] + bw / 2) / img_w
        boxes[:, :, p, 3] = (cy[:, None] + bh / 2) / img_h
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          boxes.shape).copy()
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(var))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference box_coder)."""
    def f(pb, pv, tb):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[..., 2] - pb[..., 0] + norm
        ph = pb[..., 3] - pb[..., 1] + norm
        pcx = pb[..., 0] + pw * 0.5
        pcy = pb[..., 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[..., 2] - tb[..., 0] + norm
            th = tb[..., 3] - tb[..., 1] + norm
            tcx = tb[..., 0] + tw * 0.5
            tcy = tb[..., 1] + th * 0.5
            out = jnp.stack([
                (tcx[:, None] - pcx[None, :]) / pw[None, :],
                (tcy[:, None] - pcy[None, :]) / ph[None, :],
                jnp.log(tw[:, None] / pw[None, :]),
                jnp.log(th[:, None] / ph[None, :])], axis=-1)
            return out / pv[None, :, :]
        # decode: target [R, P, 4] deltas against priors broadcast on axis
        d = tb * pv[None, :, :] if pv.ndim == 2 else tb * pv
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (v[None, :] for v in (pw, ph, pcx, pcy))
        else:
            pw_, ph_, pcx_, pcy_ = (v[:, None] for v in (pw, ph, pcx, pcy))
        cx = d[..., 0] * pw_ + pcx_
        cy = d[..., 1] * ph_ + pcy_
        w = jnp.exp(d[..., 2]) * pw_
        h = jnp.exp(d[..., 3]) * ph_
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - norm, cy + h * 0.5 - norm], -1)

    return apply_op(f, prior_box, prior_box_var, target_box,
                    op_name="box_coder")


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Decode YOLOv3 head output to boxes+scores (reference yolo_box)."""
    if iou_aware:
        raise NotImplementedError(
            "iou_aware yolo_box (extra per-anchor IoU channels) is not "
            "implemented; pass iou_aware=False")

    def f(xv, imgv):
        N, C, H, W = xv.shape
        na = len(anchors) // 2
        an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
        pred = xv.reshape(N, na, 5 + class_num, H, W)
        gx = (jax.nn.sigmoid(pred[:, :, 0]) * scale_x_y
              - (scale_x_y - 1) / 2 + jnp.arange(W)[None, None, None, :])
        gy = (jax.nn.sigmoid(pred[:, :, 1]) * scale_x_y
              - (scale_x_y - 1) / 2 + jnp.arange(H)[None, None, :, None])
        in_w, in_h = W * downsample_ratio, H * downsample_ratio
        bw = jnp.exp(pred[:, :, 2]) * an[None, :, 0, None, None] / in_w
        bh = jnp.exp(pred[:, :, 3]) * an[None, :, 1, None, None] / in_h
        cx = gx / W
        cy = gy / H
        conf = jax.nn.sigmoid(pred[:, :, 4])
        probs = jax.nn.sigmoid(pred[:, :, 5:]) * conf[:, :, None]
        mask = (conf > conf_thresh).astype(xv.dtype)
        imw = imgv[:, 1].astype(jnp.float32)
        imh = imgv[:, 0].astype(jnp.float32)
        x1 = (cx - bw / 2) * imw[:, None, None, None]
        y1 = (cy - bh / 2) * imh[:, None, None, None]
        x2 = (cx + bw / 2) * imw[:, None, None, None]
        y2 = (cy + bh / 2) * imh[:, None, None, None]
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw[:, None, None, None] - 1)
            y1 = jnp.clip(y1, 0, imh[:, None, None, None] - 1)
            x2 = jnp.clip(x2, 0, imw[:, None, None, None] - 1)
            y2 = jnp.clip(y2, 0, imh[:, None, None, None] - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1) * mask[..., None]
        boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(N, -1, 4)
        scores = (probs * mask[:, :, None]).transpose(0, 1, 3, 4, 2)
        scores = scores.reshape(N, -1, class_num)
        return boxes, scores

    return apply_op(f, x, img_size, op_name="yolo_box")


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference yolo_loss): coordinate + objectness
    + classification terms over anchor-matched ground truths."""
    def f(xv, gb, gl, *maybe_gs):
        N, C, H, W = xv.shape
        na = len(anchor_mask)
        an_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
        an = an_all[jnp.asarray(anchor_mask)]
        pred = xv.reshape(N, na, 5 + class_num, H, W)
        in_w, in_h = W * downsample_ratio, H * downsample_ratio

        px = jax.nn.sigmoid(pred[:, :, 0])
        py = jax.nn.sigmoid(pred[:, :, 1])
        pw = pred[:, :, 2]
        ph = pred[:, :, 3]
        pobj = pred[:, :, 4]
        pcls = pred[:, :, 5:]

        B = gb.shape[1]
        # gt in [0,1] cx cy w h
        gcx, gcy = gb[..., 0], gb[..., 1]
        gw, gh = gb[..., 2], gb[..., 3]
        gi = jnp.clip((gcx * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gcy * H).astype(jnp.int32), 0, H - 1)
        # best anchor per gt by wh IoU against the FULL anchor set
        gwh = jnp.stack([gw * in_w, gh * in_h], -1)   # [N, B, 2]
        inter = jnp.minimum(gwh[:, :, None, :], an_all[None, None]) \
            .prod(-1)
        union = (gwh.prod(-1)[:, :, None] + an_all.prod(-1)[None, None]
                 - inter)
        iou_a = inter / jnp.maximum(union, 1e-10)
        best = jnp.argmax(iou_a, axis=-1)             # [N, B]
        mask_vec = jnp.asarray(anchor_mask)
        # local anchor index or -1 when the best anchor isn't in this head
        local = jnp.argmax(
            (best[..., None] == mask_vec[None, None]), -1)
        in_head = jnp.any(best[..., None] == mask_vec[None, None], -1)
        valid = in_head & (gw > 0)

        tx = gcx * W - gi
        ty = gcy * H - gj
        tw = jnp.log(jnp.maximum(gwh[..., 0], 1e-4)
                     / an[local][..., 0])
        th = jnp.log(jnp.maximum(gwh[..., 1], 1e-4)
                     / an[local][..., 1])

        nidx = jnp.arange(N)[:, None].repeat(B, 1)

        def gather(p):
            return p[nidx, local, gj, gi]

        lw = (2.0 - gw * gh)
        vz = valid.astype(jnp.float32)
        loss_xy = (vz * lw * ((gather(px) - tx) ** 2
                              + (gather(py) - ty) ** 2)).sum(-1)
        loss_wh = (vz * lw * ((gather(pw) - tw) ** 2
                              + (gather(ph) - th) ** 2)).sum(-1)
        gt_w = vz if not maybe_gs else vz * maybe_gs[0]  # mixup soft labels
        obj_target = jnp.zeros((N, na, H, W))
        obj_target = obj_target.at[nidx, local, gj, gi].max(gt_w)
        bce = lambda lg, t: jnp.maximum(lg, 0) - lg * t + jnp.log1p(
            jnp.exp(-jnp.abs(lg)))
        # ignore mask (reference ignore_thresh): negatives whose predicted
        # box overlaps ANY gt above the threshold contribute no
        # objectness loss
        pbx = (jax.nn.sigmoid(pred[:, :, 0])
               + jnp.arange(W)[None, None, None, :]) / W
        pby = (jax.nn.sigmoid(pred[:, :, 1])
               + jnp.arange(H)[None, None, :, None]) / H
        pbw = jnp.exp(jnp.clip(pw, -10, 10)) \
            * an[None, :, 0, None, None] / in_w
        pbh = jnp.exp(jnp.clip(ph, -10, 10)) \
            * an[None, :, 1, None, None] / in_h
        px1, px2 = pbx - pbw / 2, pbx + pbw / 2
        py1, py2 = pby - pbh / 2, pby + pbh / 2
        gx1 = (gcx - gw / 2)[:, None, None, None, :]
        gx2 = (gcx + gw / 2)[:, None, None, None, :]
        gy1 = (gcy - gh / 2)[:, None, None, None, :]
        gy2 = (gcy + gh / 2)[:, None, None, None, :]
        ix = jnp.maximum(jnp.minimum(px2[..., None], gx2)
                         - jnp.maximum(px1[..., None], gx1), 0)
        iy2 = jnp.maximum(jnp.minimum(py2[..., None], gy2)
                          - jnp.maximum(py1[..., None], gy1), 0)
        inter_a = ix * iy2
        union_a = (pbw * pbh)[..., None] + (gw * gh)[:, None, None,
                                                     None, :] - inter_a
        best_iou = jnp.where((gw > 0)[:, None, None, None, :],
                             inter_a / jnp.maximum(union_a, 1e-10),
                             0.0).max(-1)
        noobj_w = (best_iou < ignore_thresh).astype(jnp.float32)
        obj_w = jnp.where(obj_target > 0, 1.0, noobj_w)
        loss_obj = (obj_w * bce(pobj, obj_target)).sum((1, 2, 3))
        smooth = 1.0 / class_num if use_label_smooth else 0.0
        cls_t = jax.nn.one_hot(gl, class_num) * (1 - smooth) + \
            smooth / class_num
        pc = pcls[nidx, local, :, gj, gi]
        loss_cls = (gt_w[..., None] * bce(pc, cls_t)).sum((-1, -2))
        return loss_xy + loss_wh + loss_obj + loss_cls

    args = (x, gt_box, gt_label) + (() if gt_score is None else (gt_score,))
    return apply_op(f, *args, op_name="yolo_loss")


# ---------------------------------------------------------------------------
# Deformable conv
# ---------------------------------------------------------------------------


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference deform_conv2d / phi
    deformable_conv kernel): bilinear-sample the input at offset-shifted
    taps, then a dense 1x1-style contraction with the kernel."""
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) else tuple(
        dilation)

    def f(xv, ov, wv, *rest):
        bias_v = mask_v = None
        rest = list(rest)
        if bias is not None:
            bias_v = rest.pop(0)
        if mask is not None:
            mask_v = rest.pop(0)
        N, C, H, W = xv.shape
        OC, ICg, KH, KW = wv.shape
        OH = (H + 2 * pd[0] - dl[0] * (KH - 1) - 1) // st[0] + 1
        OW = (W + 2 * pd[1] - dl[1] * (KW - 1) - 1) // st[1] + 1
        xp = jnp.pad(xv, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
        Hp, Wp = xp.shape[2], xp.shape[3]
        oy = jnp.arange(OH) * st[0]
        ox = jnp.arange(OW) * st[1]
        ky = jnp.arange(KH) * dl[0]
        kx = jnp.arange(KW) * dl[1]
        base_y = oy[:, None, None, None] + ky[None, None, :, None]
        base_x = ox[None, :, None, None] + kx[None, None, None, :]
        off = ov.reshape(N, deformable_groups, KH * KW, 2, OH, OW)
        off_y = off[:, :, :, 0].reshape(N, deformable_groups, KH, KW, OH,
                                        OW).transpose(0, 1, 4, 5, 2, 3)
        off_x = off[:, :, :, 1].reshape(N, deformable_groups, KH, KW, OH,
                                        OW).transpose(0, 1, 4, 5, 2, 3)
        sy = base_y[None, None] + off_y   # [N, dg, OH, OW, KH, KW]
        sx = base_x[None, None] + off_x

        bilinear = _bilinear_gather

        cpg = C // deformable_groups

        def per_image(img, syi, sxi, mi):
            cols = []
            for dg in range(deformable_groups):
                sub = img[dg * cpg:(dg + 1) * cpg]
                v = bilinear(sub, syi[dg], sxi[dg])  # [cpg, OH, OW, KH, KW]
                if mi is not None:
                    v = v * mi[dg][None]
                cols.append(v)
            return jnp.concatenate(cols, axis=0)      # [C, OH, OW, KH, KW]

        if mask_v is not None:
            mk = mask_v.reshape(N, deformable_groups, KH, KW, OH, OW) \
                .transpose(0, 1, 4, 5, 2, 3)
        else:
            mk = [None] * N
        cols = jax.vmap(per_image)(xp, sy, sx,
                                   mk if mask_v is not None else None) \
            if mask_v is not None else jax.vmap(
                lambda img, a, b: per_image(img, a, b, None))(xp, sy, sx)
        # contraction: groups split over channels
        cols = cols.reshape(N, groups, C // groups, OH, OW, KH, KW)
        wv_g = wv.reshape(groups, OC // groups, ICg, KH, KW)
        out = jnp.einsum("ngcxykl,gockl->ngoxy", cols, wv_g)
        out = out.reshape(N, OC, OH, OW)
        if bias_v is not None:
            out = out + bias_v[None, :, None, None]
        return out

    args = [x, offset, weight]
    if bias is not None:
        args.append(bias)
    if mask is not None:
        args.append(mask)
    return apply_op(f, *args, op_name="deform_conv2d")


class DeformConv2D(Layer):
    """reference vision/ops.py DeformConv2D layer."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, ks[0], ks[1]],
            attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=self._stride, padding=self._padding,
                             dilation=self._dilation,
                             deformable_groups=self._deformable_groups,
                             groups=self._groups, mask=mask)


# ---------------------------------------------------------------------------
# FPN / proposals / files
# ---------------------------------------------------------------------------


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference
    distribute_fpn_proposals)."""
    rois = np.asarray(unwrap(fpn_rois), np.float32)
    off = 1.0 if pixel_offset else 0.0
    w = np.maximum(rois[:, 2] - rois[:, 0] + off, 0)
    h = np.maximum(rois[:, 3] - rois[:, 1] + off, 0)
    scale = np.sqrt(w * h)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    if rois_num is not None:
        rn = np.asarray(unwrap(rois_num), np.int64)
        img_of = np.repeat(np.arange(len(rn)), rn)
    else:
        rn = np.asarray([len(rois)], np.int64)
        img_of = np.zeros(len(rois), np.int64)
    outs, idxs, nums = [], [], []
    for L in range(min_level, max_level + 1):
        sel = np.nonzero(lvl == L)[0]
        outs.append(Tensor(jnp.asarray(rois[sel])))
        idxs.extend(sel.tolist())
        # per-IMAGE counts at this level (downstream roi ops need the
        # image grouping, not just the level total)
        per_img = np.bincount(img_of[sel], minlength=len(rn))
        nums.append(Tensor(jnp.asarray(per_img.astype(np.int32))))
    restore = np.argsort(np.asarray(idxs, np.int64))
    res = [outs, Tensor(jnp.asarray(restore))]
    if rois_num is not None:
        res.append(nums)
    return tuple(res)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference generate_proposals): decode
    deltas at anchors, clip, filter small, NMS."""
    sc = np.asarray(unwrap(scores), np.float32)
    bd = np.asarray(unwrap(bbox_deltas), np.float32)
    im = np.asarray(unwrap(img_size), np.float32)
    an = np.asarray(unwrap(anchors), np.float32).reshape(-1, 4)
    var = np.asarray(unwrap(variances), np.float32).reshape(-1, 4)
    N = sc.shape[0]
    all_rois, all_scores, nums = [], [], []
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)
        d = bd[n].transpose(1, 2, 0).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], an[order], var[order]
        aw = a[:, 2] - a[:, 0]
        ah = a[:, 3] - a[:, 1]
        acx = a[:, 0] + aw / 2
        acy = a[:, 1] + ah / 2
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        w = np.exp(np.minimum(v[:, 2] * d[:, 2], 10)) * aw
        h = np.exp(np.minimum(v[:, 3] * d[:, 3], 10)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                         -1)
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, im[n, 1] - 1)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, im[n, 0] - 1)
        ok = ((boxes[:, 2] - boxes[:, 0] >= min_size)
              & (boxes[:, 3] - boxes[:, 1] >= min_size))
        boxes, s = boxes[ok], s[ok]
        keep = np.asarray(unwrap(nms(Tensor(jnp.asarray(boxes)),
                                     iou_threshold=nms_thresh,
                                     scores=Tensor(jnp.asarray(s)))))
        keep = keep[:post_nms_top_n]
        all_rois.append(boxes[keep])
        all_scores.append(s[keep])
        nums.append(len(keep))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0)))
    rscores = Tensor(jnp.asarray(np.concatenate(all_scores, 0)))
    if return_rois_num:
        return rois, rscores, Tensor(jnp.asarray(np.asarray(nums,
                                                            np.int32)))
    return rois, rscores


def read_file(filename, name=None):
    """Raw file bytes as a uint8 tensor (reference read_file)."""
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode JPEG bytes to CHW uint8 (reference decode_jpeg — nvjpeg on
    GPU; PIL is the host decoder here)."""
    try:
        import io

        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise ModuleNotFoundError(
            "decode_jpeg needs Pillow for host-side decoding") from e
    raw = bytes(np.asarray(unwrap(x), np.uint8).tobytes())
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "unchanged"):
        img = img.convert("RGB") if mode == "rgb" else img
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
