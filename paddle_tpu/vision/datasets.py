"""paddle.vision.datasets parity (reference: python/paddle/vision/datasets/).

Zero-egress environment: datasets load from LOCAL files (the reference's
download=True path needs network); `mode="random"` generates deterministic
synthetic data with the right shapes for pipeline tests."""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder"]


class MNIST(Dataset):
    """reference datasets/mnist.py — idx-format loader + synthetic mode."""

    def __init__(self, image_path: Optional[str] = None,
                 label_path: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False,
                 backend: str = "cv2"):
        self.transform = transform
        self.mode = mode
        if image_path and os.path.exists(image_path):
            self.images = self._read_images(image_path)
            self.labels = self._read_labels(label_path)
        else:
            if download:
                raise RuntimeError(
                    "no network egress in this environment; place idx files "
                    "locally and pass image_path/label_path")
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = 1024 if mode == "train" else 256
            self.images = rng.randint(0, 256, (n, 28, 28), dtype=np.uint8)
            self.labels = rng.randint(0, 10, (n, 1)).astype(np.int64)

    @staticmethod
    def _read_images(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)

    @staticmethod
    def _read_labels(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            _, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), np.uint8).astype(
                np.int64).reshape(n, 1)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """reference datasets/cifar.py — python-pickle batches + synthetic mode."""

    N_CLASSES = 10

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 transform: Optional[Callable] = None, download: bool = False,
                 backend: str = "cv2"):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            self.data, self.labels = self._load(data_file, mode)
        else:
            if download:
                raise RuntimeError("no network egress; pass data_file")
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = 1024 if mode == "train" else 256
            self.data = rng.randint(0, 256, (n, 3, 32, 32), dtype=np.uint8)
            self.labels = rng.randint(0, self.N_CLASSES, (n,)).astype(np.int64)

    def _load(self, path, mode):
        imgs, labels = [], []
        with tarfile.open(path) as tf:
            names = [m for m in tf.getmembers()
                     if ("data_batch" in m.name if mode == "train"
                         else "test_batch" in m.name)]
            for m in names:
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                imgs.append(np.asarray(d[b"data"]).reshape(-1, 3, 32, 32))
                key = b"labels" if b"labels" in d else b"fine_labels"
                labels.append(np.asarray(d[key], np.int64))
        return np.concatenate(imgs), np.concatenate(labels)

    def __getitem__(self, idx):
        img = self.data[idx].transpose(1, 2, 0)  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    N_CLASSES = 100


class DatasetFolder(Dataset):
    """reference datasets/folder.py — class-per-subdir image tree (numpy .npy
    files in this no-PIL environment)."""

    def __init__(self, root: str, loader=None, extensions=(".npy",),
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or np.load
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.endswith(tuple(extensions)):
                    self.samples.append((os.path.join(cdir, fn),
                                         self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat-folder image dataset (reference datasets/folder.py
    ImageFolder): every image under root, no labels."""

    _EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".ppm", ".webp")

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os

        exts = tuple(e.lower() for e in (extensions or self._EXTS))
        self.root = root
        self.transform = transform
        self.loader = loader
        samples = []
        for dirpath, _, names in sorted(os.walk(root)):
            for fn in sorted(names):
                path = os.path.join(dirpath, fn)
                ok = (is_valid_file(path) if is_valid_file
                      else fn.lower().endswith(exts))
                if ok:
                    samples.append(path)
        if not samples:
            raise RuntimeError(f"Found 0 files in {root}")
        self.samples = samples

    def _load(self, path):
        if self.loader is not None:
            return self.loader(path)
        from .image import image_load

        img = image_load(path)
        return np.asarray(img)

    def __getitem__(self, idx):
        img = self._load(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Oxford-102 flowers (reference datasets/flowers.py). Zero-egress:
    pass data_file (102flowers.tgz extracted dir with jpg/) + label_file
    (imagelabels.mat) + setid_file (setid.mat)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend=None):
        import os

        if not (data_file and os.path.exists(data_file)):
            raise RuntimeError(
                "Flowers: no local data. Fetch 102flowers.tgz / "
                "imagelabels.mat / setid.mat on a connected machine and "
                "pass their paths (this build has no network egress).")
        import scipy.io

        labels = scipy.io.loadmat(label_file)["labels"].ravel()
        setid = scipy.io.loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self.indexes = setid[key].ravel()
        self.labels = labels
        self.data_dir = data_file
        self.transform = transform

    def __getitem__(self, idx):
        import os

        img_idx = int(self.indexes[idx])
        path = os.path.join(self.data_dir, f"image_{img_idx:05d}.jpg")
        from .image import image_load

        img = np.asarray(image_load(path))
        if self.transform is not None:
            img = self.transform(img)
        label = np.asarray(self.labels[img_idx - 1] - 1, np.int64)
        return img, label

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (reference datasets/voc2012.py).
    Zero-egress: data_file = extracted VOCdevkit/VOC2012 directory."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        import os

        if not (data_file and os.path.isdir(data_file)):
            raise RuntimeError(
                "VOC2012: no local data. Extract VOCtrainval_11-May-2012 "
                "on a connected machine and pass VOCdevkit/VOC2012 as "
                "data_file (this build has no network egress).")
        name = {"train": "train", "valid": "val", "test": "val",
                "trainval": "trainval"}[mode]
        list_file = os.path.join(data_file, "ImageSets", "Segmentation",
                                 name + ".txt")
        with open(list_file) as f:
            self.ids = [ln.strip() for ln in f if ln.strip()]
        self.root = data_file
        self.transform = transform

    def __getitem__(self, idx):
        import os

        from .image import image_load

        name = self.ids[idx]
        img = np.asarray(image_load(
            os.path.join(self.root, "JPEGImages", name + ".jpg")))
        lbl = np.asarray(image_load(
            os.path.join(self.root, "SegmentationClass", name + ".png")))
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return len(self.ids)


__all__ += ["ImageFolder", "Flowers", "VOC2012"]
