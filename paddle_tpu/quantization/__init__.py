"""paddle.quantization parity (reference: python/paddle/quantization/ —
QuantConfig, PTQ observers, QAT fake-quant, quanted layer swap).

TPU-native notes: int8 inference on TPU rides XLA's int8 matmul; training-
time quantization here is simulated (fake-quant in fp) exactly like the
reference's QAT — scale observation + round-to-nearest with straight-
through gradients (custom_vjp identity)."""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["quant", "dequant", "fake_quant", "AbsmaxObserver",
           "BaseObserver", "FakeQuanterWithAbsMax", "QuantConfig", "QAT",
           "PTQ", "QuantedLinear"]


# -- functional core ---------------------------------------------------------


def quant(x, scale, bits: int = 8):
    """Real quantize: fp → int (reference quant kernels)."""
    qmax = 2 ** (bits - 1) - 1
    v = x._value if isinstance(x, Tensor) else x
    s = scale._value if isinstance(scale, Tensor) else scale
    return Tensor(jnp.clip(jnp.round(v / s * qmax), -qmax - 1, qmax)
                  .astype(jnp.int8 if bits == 8 else jnp.int32))


def dequant(x, scale, bits: int = 8):
    qmax = 2 ** (bits - 1) - 1
    v = x._value if isinstance(x, Tensor) else x
    s = scale._value if isinstance(scale, Tensor) else scale
    return Tensor(v.astype(jnp.float32) * s / qmax)


@jax.custom_vjp
def _fake_quant(v, scale, qmax):
    q = jnp.clip(jnp.round(v / scale * qmax), -qmax - 1, qmax)
    return q * scale / qmax


def _fq_fwd(v, scale, qmax):
    return _fake_quant(v, scale, qmax), (v, scale)


def _fq_bwd(res, g):
    # straight-through estimator: pass gradient where |v| <= scale
    v, scale = res
    mask = (jnp.abs(v) <= scale).astype(g.dtype)
    return g * mask, jnp.zeros_like(scale), None


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


def fake_quant(x, scale, bits: int = 8):
    """Simulated quantization with STE gradient (QAT core)."""
    qmax = float(2 ** (bits - 1) - 1)
    s = scale._value if isinstance(scale, Tensor) else jnp.asarray(scale)
    return apply_op(lambda v: _fake_quant(v, s, qmax), x,
                    op_name="fake_quant")


# -- observers ---------------------------------------------------------------


class BaseObserver(Layer):
    """reference quantization/observer.py BaseObserver."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self._quant_bits = quant_bits

    def scales(self):
        raise NotImplementedError

    def bit_length(self):
        return self._quant_bits


class AbsmaxObserver(BaseObserver):
    """Running abs-max scale observer (reference AbsmaxObserver)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__(quant_bits)
        self._max = 1e-9

    def forward(self, x):
        v = x._value if isinstance(x, Tensor) else x
        if not isinstance(v, jax.core.Tracer):  # calibration is eager-only
            self._max = max(self._max, float(jnp.max(jnp.abs(v))))
        return x

    def scales(self):
        return Tensor(jnp.asarray(self._max, jnp.float32))


class FakeQuanterWithAbsMax(BaseObserver):
    """QAT fake-quanter (reference FakeQuanterWithAbsMaxObserver): observes
    abs-max and applies STE fake-quant in forward."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9,
                 name=None):
        super().__init__(quant_bits)
        self._moving_rate = moving_rate
        self._scale = None  # set from the FIRST batch's absmax (reference
        # seeds the state with the first observation; ramping from ~0 would
        # mask every STE gradient early in training)

    def forward(self, x):
        v = x._value if isinstance(x, Tensor) else x
        # observation is a host-side statistic: skip under trace (jit sees a
        # tracer; the frozen scale is used) and when not training
        if self.training and not isinstance(v, jax.core.Tracer):
            cur = float(jnp.max(jnp.abs(v)))
            if self._scale is None:
                self._scale = max(cur, 1e-9)
            else:
                r = self._moving_rate
                self._scale = max(r * self._scale + (1 - r) * cur, 1e-9)
        return fake_quant(x, self._scale if self._scale is not None else 1.0,
                          self._quant_bits)

    def scales(self):
        return Tensor(jnp.asarray(self._scale or 1e-9, jnp.float32))


# -- quanted layers ----------------------------------------------------------


class QuantedLinear(Layer):
    """Linear with weight+activation fake-quant (reference
    nn/quant/qat/linear.py QuantedLinear)."""

    def __init__(self, linear, q_config=None):
        super().__init__()
        self.linear = linear
        bits = (q_config.weight_bits if q_config else 8)
        self.weight_quanter = FakeQuanterWithAbsMax(bits)
        self.activation_quanter = FakeQuanterWithAbsMax(
            q_config.activation_bits if q_config else 8)

    def forward(self, x):
        from ..nn import functional as F

        x = self.activation_quanter(x)
        w = self.weight_quanter(self.linear.weight)
        return F.linear(x, w, self.linear.bias)


class QuantConfig:
    """reference quantization/config.py QuantConfig."""

    def __init__(self, activation=None, weight=None, weight_bits: int = 8,
                 activation_bits: int = 8):
        self.activation = activation
        self.weight = weight
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self._layer_map: Dict[Type, Type] = {}
        from ..nn.layer.common import Linear

        self._layer_map[Linear] = QuantedLinear

    def add_layer_config(self, layer_types, activation=None, weight=None):
        return self

    def add_type_config(self, layer_types, activation=None, weight=None):
        return self


def _swap_layers(model: Layer, cfg: QuantConfig):
    for name, sub in list(model._sub_layers.items()):
        swapped = cfg._layer_map.get(type(sub))
        if swapped is not None:
            model._sub_layers[name] = swapped(sub, cfg)
        else:
            _swap_layers(sub, cfg)
    return model


def _maybe_copy(model: Layer, inplace: bool) -> Layer:
    if inplace:
        return model
    import copy

    return copy.deepcopy(model)


class QAT:
    """Quantization-aware training driver (reference quantization/qat.py)."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        return _swap_layers(_maybe_copy(model, inplace), self._config)

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        return model  # fake-quant layers already carry final scales


class PTQ:
    """Post-training quantization driver (reference quantization/ptq.py):
    insert observers, run calibration data through, convert freezes the
    observed scales into the quanted layers."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self._config = config or QuantConfig()
        self._observers: List[AbsmaxObserver] = []
        self._obs_by_layer: Dict[int, AbsmaxObserver] = {}
        self._hooks = []

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        cfg = self._config
        model = _maybe_copy(model, inplace)

        def attach(m):
            for name, sub in list(m._sub_layers.items()):
                from ..nn.layer.common import Linear

                if isinstance(sub, Linear):
                    obs = AbsmaxObserver(cfg.activation_bits)
                    self._observers.append(obs)
                    self._obs_by_layer[id(sub)] = obs
                    self._hooks.append(sub.register_forward_pre_hook(
                        lambda l, inputs, _o=obs: (_o(inputs[0]),)))
                else:
                    attach(sub)

        attach(model)
        return model

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        """Swap to quanted layers and FREEZE the calibrated scales
        (the reference's scale-transfer step)."""
        for h in self._hooks:
            h.remove()
        self._hooks = []

        def swap(m):
            for name, sub in list(m._sub_layers.items()):
                from ..nn.layer.common import Linear

                if isinstance(sub, Linear):
                    ql = QuantedLinear(sub, self._config)
                    obs = self._obs_by_layer.get(id(sub))
                    if obs is not None:
                        ql.activation_quanter._scale = float(
                            obs.scales()._value)
                    ql.weight_quanter._scale = float(
                        jnp.max(jnp.abs(sub.weight._value)))
                    ql.eval()
                    m._sub_layers[name] = ql
                else:
                    swap(sub)

        swap(model)
        return model


class BaseQuanter(BaseObserver):
    """Base class for trainable quanters (reference quantization/base_quanter
    .py) — same contract as observers plus scales()/zero_points()."""

    def scales(self):
        return getattr(self, "_scale", None)

    def zero_points(self):
        return getattr(self, "_zero_point", 0)


def quanter(class_name: str):
    """Class decorator registering a quanter under a factory name
    (reference quantization/factory.py:quanter): creates a ``<name>``
    factory whose __call__ instantiates the decorated class."""
    def wrapper(cls):
        class _Factory:
            def __init__(self, *args, **kwargs):
                self._args, self._kwargs = args, kwargs

            def _instance(self, layer=None):
                return cls(*self._args, **self._kwargs)

            __call__ = _instance

        _Factory.__name__ = class_name
        globals()[class_name] = _Factory
        return cls

    return wrapper


__all__ += ["BaseQuanter", "quanter"]

# serving-side KV quantization math (int8 pages, per-page-per-head
# absmax scales): ONE home shared by the paged-cache store helpers,
# the fused-dequant attention kernel, and the A/B divergence harness
# — and the intended import point for future weight-side int8 too
from . import kv  # noqa: E402
from .kv import (KV_DTYPES, KV_QMAX, KV_SCALE_FLOOR,  # noqa: E402,F401
                 dequant_scale, dequantize_page, max_logit_divergence,
                 quant_store_rows, quantize_page)

__all__ += ["kv", "KV_DTYPES", "KV_QMAX", "KV_SCALE_FLOOR",
            "dequant_scale", "quantize_page", "dequantize_page",
            "quant_store_rows", "max_logit_divergence"]
