"""Shared int8 KV-cache quantization math (serving path).

ONE home for the absmax quantize/dequantize arithmetic the quantized
KV serving path uses everywhere — the page-pool store helpers
(inference/paged_cache.py), the fused-dequant read kernel
(ops/paged_attention.py), and the A/B divergence harness
(tools/serve_bench.py --kv-ab) all import from here, and a future
weight-side int8 path is expected to as well. Keeping the rounding and
scale conventions in one module is what makes "bounded divergence"
a checkable contract instead of N slightly-different quantizers.

Conventions (symmetric absmax, per-page-per-KV-head):

- a scale ``s`` is the running ABSMAX of everything quantized against
  it (never below :data:`KV_SCALE_FLOOR` — dequant of a never-written
  page must be finite and ~0, not NaN);
- quantize: ``q = clip(round(x / s * KV_QMAX), -KV_QMAX, KV_QMAX)``
  (symmetric [-127, 127]: 0.0 round-trips exactly and the error bound
  is the same both sides);
- dequantize: ``x̂ = q * s / KV_QMAX`` — i.e. ``q *``
  :func:`dequant_scale` ``(s)``. With ``s >= absmax(x)`` the
  round-trip error is at most ``s / (2 * KV_QMAX)`` per element;
- page granularity: one f32 scale per (page, kv_head) — heads have
  very different dynamic ranges, and a page is the grain the pool
  copies/shares at, so scales ride the page table exactly like pages
  do (CoW copies them, warm prefix admissions gather through them).

RUNNING absmax (:func:`quant_store_rows`): decode appends tokens into
a page one step at a time, so a page's absmax can GROW after earlier
rows were already quantized. A growth event re-quantizes the page's
existing int8 rows by the old/new scale ratio (one extra rounding —
this is the "bounded, not bitwise" part of the int8 contract; the
per-page bound above still holds for the final scale).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["KV_DTYPES", "KV_QMAX", "KV_SCALE_FLOOR", "dequant_scale",
           "quantize_page", "dequantize_page", "quant_store_rows",
           "max_logit_divergence"]

# pool storage dtypes the paged engine accepts: "bf16" is the
# NON-quantized path (pools in the model's configured cache dtype —
# bf16 on production configs, f32 on the CPU-tiny test model) and
# stays bitwise-identical to pre-quantization behavior; "int8" stores
# pages int8 with per-page-per-head scales
KV_DTYPES = ("bf16", "int8")

KV_QMAX = 127.0          # symmetric int8 range [-127, 127]
KV_SCALE_FLOOR = 1e-8    # scales never 0: dequant stays finite


def dequant_scale(scale):
    """Per-element dequant multiplier for absmax scale(s) ``scale``:
    ``x̂ = q * dequant_scale(s)``. The fused read kernel applies this
    inside the attention program so the HBM read stays int8."""
    return scale / KV_QMAX


def quantize_page(page, scale):
    """Quantize one page's rows ``[..., H, D]`` (float) against
    per-head absmax ``scale [H]`` (or any shape broadcastable over the
    head axis at -2). Callers own ``scale >= absmax(page)`` — values
    above the scale saturate at ±KV_QMAX."""
    s = jnp.maximum(jnp.asarray(scale, jnp.float32), KV_SCALE_FLOOR)
    q = jnp.round(page.astype(jnp.float32)
                  / jnp.expand_dims(s, -1) * KV_QMAX)
    return jnp.clip(q, -KV_QMAX, KV_QMAX).astype(jnp.int8)


def dequantize_page(qpage, scale):
    """Inverse of :func:`quantize_page` (f32 result)."""
    s = jnp.asarray(scale, jnp.float32)
    return qpage.astype(jnp.float32) * jnp.expand_dims(
        dequant_scale(s), -1)


def quant_store_rows(pool, scales, pages, offs, rows):
    """Running-absmax int8 store of token rows into a paged pool —
    the ONE write primitive every quantized KV write path reduces to
    (single-token decode scatter, bucket-width prefill install, the
    masked warm-suffix scatter, and the W-wide speculative writes).

    pool: [P, ps, H, D] int8; scales: [P, H] f32 (running absmax per
    page per head); pages: [N] int32 target page per row, with the
    OUT-OF-RANGE sentinel ``P`` for rows to drop (the ``write_tokens``
    convention — dead slots, unmapped positions); offs: [N] int32 row
    offset within each page; rows: [N, H, D] float.

    Per call (pure, jittable — rides inside compiled programs):

    1. per-row per-head absmax joins the target pages' running scales
       via a scatter-max (rows landing in the same page compose
       correctly in one shot);
    2. pages whose scale GREW re-quantize their existing int8 rows by
       ``old/new`` (ratio 1 for untouched pages — exact no-op);
    3. the new rows store quantized against the updated scales.

    Writes never touch pages other than ``pages`` (dropped rows touch
    nothing), so shared/read-only pages are exactly as safe as with
    the unquantized scatter. Returns ``(pool, scales)``.
    """
    P = pool.shape[0]
    safe = jnp.minimum(pages, P - 1)        # gather-safe page index
    a = jnp.max(jnp.abs(rows.astype(jnp.float32)), axis=-1)   # [N, H]
    old = jnp.maximum(scales, KV_SCALE_FLOOR)
    new_scales = jnp.maximum(old.at[pages].max(a, mode="drop"),
                             KV_SCALE_FLOOR)
    # re-quantize grown pages' existing rows (identical duplicate
    # writes when several rows hit one page — deterministic content).
    # Gated on ACTUAL growth: steady-state decode (absmax long
    # established, ratio 1 everywhere) must not pay the full-page
    # gather + rewrite per step — that write amplification sits on
    # the exact HBM-bound path int8 exists to relieve.
    r = (old / new_scales)[safe]                              # [N, H]

    def _requant(p):
        repaged = jnp.clip(
            jnp.round(p[safe].astype(jnp.float32)
                      * r[:, None, :, None]),
            -KV_QMAX, KV_QMAX).astype(jnp.int8)
        return p.at[pages].set(repaged, mode="drop")

    pool = jax.lax.cond(jnp.any(r < 1.0), _requant, lambda p: p,
                        pool)
    q = quantize_page(rows, new_scales[safe])
    pool = pool.at[pages, offs].set(q, mode="drop")
    return pool, new_scales


def max_logit_divergence(eng_a, eng_b, prompts, cfg=None,
                         steps: int = 16):
    """Plain-vs-quantized logit-divergence probe: admit the same
    prompts (greedy) into two IDLE continuous-batching engines, step
    them one decode token at a time, and before each step compare the
    next-token logits both engines would sample from. Returns
    ``{"max_logit_div", "mean_logit_div", "token_flips", "tokens"}``.

    This is the serving correctness bar for ``kv_dtype="int8"``:
    bounded logit divergence and (on the reference tiny model) ZERO
    token flips — the harness ``tools/serve_bench.py --kv-ab`` runs
    and records (``serve_kv_quant_max_logit_div``). Both engines are
    driven through their public admission/segment path, so the probe
    exercises the real store/read pipeline (quantize-on-store, fused
    dequant) — the extra logit read per step is an eager forward whose
    cache result is discarded.

    Greedy-intended. Once a slot's argmax FLIPS the two trajectories
    feed themselves different tokens, so later logit gaps there
    measure history divergence, not quantization error — a flipped
    slot is counted once and excluded from further comparison (the
    recorded divergence is always apples-to-apples on identical
    prefixes).
    """
    import numpy as np

    from ..inference.generation import GenerationConfig

    cfg = cfg or GenerationConfig(max_new_tokens=steps)
    for eng in (eng_a, eng_b):
        if eng._slot_req:
            raise RuntimeError(
                "max_logit_divergence needs idle engines")
    for p in prompts:
        eng_a.add_request(p, cfg)
        eng_b.add_request(p, cfg)
    max_div = 0.0
    sum_div = 0.0
    flips = 0
    tokens = 0
    n = 0
    dead = set()                      # slots whose trajectories split
    for _ in range(steps):
        if not (eng_a._slot_req and eng_b._slot_req):
            break
        la = eng_a._fwd_ragged(eng_a.params, eng_a.last[:, None],
                               eng_a.caches, eng_a.lens,
                               eng_a.active_dev)[0]
        lb = eng_b._fwd_ragged(eng_b.params, eng_b.last[:, None],
                               eng_b.caches, eng_b.lens,
                               eng_b.active_dev)[0]
        live = np.asarray(eng_a.active_dev) & np.asarray(
            eng_b.active_dev)
        la = np.asarray(la[:, 0], np.float32)
        lb = np.asarray(lb[:, 0], np.float32)
        for s in np.nonzero(live)[0]:
            if int(s) in dead:
                continue
            d = float(np.max(np.abs(la[s] - lb[s])))
            max_div = max(max_div, d)
            sum_div += d
            n += 1
            tokens += 1
            if int(la[s].argmax()) != int(lb[s].argmax()):
                flips += 1
                dead.add(int(s))
        eng_a.decode_segment(1, cfg)
        eng_b.decode_segment(1, cfg)
    # drain so the engines come back idle/leak-free for the caller
    while eng_a.decode_segment(4, cfg):
        pass
    while eng_b.decode_segment(4, cfg):
        pass
    eng_a.collect_finished()
    eng_b.collect_finished()
    return {"max_logit_div": max_div,
            "mean_logit_div": (sum_div / n if n else 0.0),
            "token_flips": flips, "tokens": tokens}
