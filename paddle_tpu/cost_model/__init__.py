"""Cost model (reference: python/paddle/cost_model/cost_model.py —
profile_measure runs the program under the profiler and reports per-op
cost).

TPU-native: a static ``Program`` compiles to ONE XLA module, so the two
cost sources are (a) XLA's own static analysis (flops/bytes accessed via
``Compiled.cost_analysis``) and (b) measured wall time per program run.
Both are exposed; there is no per-op replay because XLA fuses across op
boundaries (that fusion is the point).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

__all__ = ["CostModel"]


class CostModel:
    def profile_measure(self, startup_program=None, main_program=None,
                        device: str = "tpu",
                        fetch_cost_list: Optional[List[str]] = None,
                        fetch_list=None, feed: Optional[Dict] = None,
                        iters: int = 3) -> Dict:
        """Measure the program: wall time per run + XLA cost analysis
        (reference cost_model.py:profile_measure)."""
        from ..static.executor import Executor

        exe = Executor()
        if startup_program is not None:
            exe.run(startup_program)
        feed = feed or {}
        exe.run(main_program, feed=feed, fetch_list=fetch_list)  # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            out = exe.run(main_program, feed=feed, fetch_list=fetch_list)
        wall = (time.perf_counter() - t0) / iters
        rec: Dict = {"time_ms": wall * 1e3, "device": device}
        rec.update(self.static_cost(main_program, feed=feed,
                                    fetch_list=fetch_list))
        return rec

    def static_cost(self, main_program, feed=None, fetch_list=None) -> Dict:
        """XLA static analysis: flops + bytes accessed for the compiled
        program (the Executor records its last jitted step + args)."""
        rec = getattr(main_program, "_last_step_args", None)
        if rec is None:
            return {}
        step, args = rec
        try:
            analysis = step.lower(*args).compile().cost_analysis()
            if isinstance(analysis, list):
                analysis = analysis[0] if analysis else {}
            return {"flops": float(analysis.get("flops", -1.0)),
                    "bytes_accessed":
                        float(analysis.get("bytes accessed", -1.0))}
        except Exception:
            return {}
