"""Datatype registry.

TPU-native analog of the reference's POD dtype layer
(``paddle/phi/common/data_type.h``, ``float16.h``/``bfloat16.h``): dtypes are
plain ``jnp.dtype`` objects; bfloat16 is the native TPU compute type rather
than a hand-rolled struct.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects (exposed as paddle_tpu.float32 etc.).
#
# TPU-native decision: XLA:TPU computes in 32-bit (64-bit emulation is slow
# and JAX disables x64 by default), so 64-bit dtype NAMES are kept for API
# parity but canonicalize to their 32-bit counterparts unless JAX_ENABLE_X64
# is set. This mirrors jnp's own canonicalization and keeps paddle.int64 ==
# actual array dtype consistent.
import jax

_X64 = bool(jax.config.jax_enable_x64)

float16 = jnp.dtype(jnp.float16)
bfloat16 = jnp.dtype(jnp.bfloat16)
float32 = jnp.dtype(jnp.float32)
float64 = jnp.dtype(jnp.float64) if _X64 else float32
int8 = jnp.dtype(jnp.int8)
int16 = jnp.dtype(jnp.int16)
int32 = jnp.dtype(jnp.int32)
int64 = jnp.dtype(jnp.int64) if _X64 else int32
uint8 = jnp.dtype(jnp.uint8)
uint16 = jnp.dtype(jnp.uint16)
uint32 = jnp.dtype(jnp.uint32)
bool_ = jnp.dtype(jnp.bool_)
complex64 = jnp.dtype(jnp.complex64)
complex128 = jnp.dtype(jnp.complex128) if _X64 else complex64

_NAME_TO_DTYPE = {
    "float16": float16,
    "fp16": float16,
    "half": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float": float32,
    "float64": float64,
    "fp64": float64,
    "double": float64,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int": int32,
    "int64": int64,
    "long": int64,
    "uint8": uint8,
    "uint16": uint16,
    "uint32": uint32,
    "bool": bool_,
    "complex64": complex64,
    "complex128": complex128,
}

FLOATING_DTYPES = (float16, bfloat16, float32, float64)
INTEGER_DTYPES = (int8, int16, int32, int64, uint8, uint16, uint32)


def convert_dtype(dtype) -> jnp.dtype:
    """Normalize a user-provided dtype (str / np / jnp dtype) to jnp.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _NAME_TO_DTYPE[dtype]
        except KeyError:
            raise ValueError(f"Unknown dtype name: {dtype!r}")
    return jnp.dtype(dtype)


def dtype_name(dtype) -> str:
    d = jnp.dtype(dtype)
    return d.name


def is_floating_point(dtype) -> bool:
    d = convert_dtype(dtype)
    return d in FLOATING_DTYPES


def is_integer(dtype) -> bool:
    d = convert_dtype(dtype)
    return d in INTEGER_DTYPES or d == bool_


_DEFAULT_DTYPE = [float32]


def set_default_dtype(dtype):
    """paddle.set_default_dtype parity (reference: python/paddle/framework/framework.py)."""
    d = convert_dtype(dtype)
    if d not in FLOATING_DTYPES:
        raise TypeError(f"default dtype must be floating, got {d}")
    _DEFAULT_DTYPE[0] = d


def get_default_dtype():
    return _DEFAULT_DTYPE[0]


def promote(*dtypes):
    return jnp.result_type(*dtypes)


def to_numpy_dtype(dtype):
    d = convert_dtype(dtype)
    if d == bfloat16:
        # numpy has no native bfloat16; ml_dtypes provides it via jnp
        return np.dtype(jnp.bfloat16)
    return np.dtype(d)


class finfo:
    """paddle.finfo parity (reference exposes numpy-finfo-shaped records)."""

    def __init__(self, dtype):
        d = convert_dtype(dtype)
        if d == bfloat16:
            import ml_dtypes

            info = ml_dtypes.finfo(ml_dtypes.bfloat16)
        else:
            info = np.finfo(to_numpy_dtype(d))
        self.dtype = dtype_name(d)
        self.bits = info.bits
        self.eps = float(info.eps)
        self.min = float(info.min)
        self.max = float(info.max)
        self.tiny = float(info.tiny)
        self.smallest_normal = float(info.tiny)
        self.resolution = float(info.resolution)


class iinfo:
    """paddle.iinfo parity."""

    def __init__(self, dtype):
        d = convert_dtype(dtype)
        info = np.iinfo(to_numpy_dtype(d))
        self.dtype = dtype_name(d)
        self.bits = info.bits
        self.min = int(info.min)
        self.max = int(info.max)
