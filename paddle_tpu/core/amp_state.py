"""Autocast state consulted by the eager op dispatcher (apply_op).

Architectural parity with the reference: AMP casting lives INSIDE the
generated per-op forward functions (eager_gen.py:462 EagerAmpAutoCast,
imperative/amp_auto_cast.cc AmpLevel state); here the single choke point
every eager op passes through is ``core.autograd.apply_op``, so the policy
hook lives there. Under jit the same policy applies while tracing — casts
become part of the XLA program (bf16 inputs feed the MXU directly).
"""
from __future__ import annotations

import threading
from typing import Optional, Set

import jax.numpy as jnp

__all__ = ["AmpState", "amp_state", "maybe_cast_inputs"]


class AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.level = "O0"            # O0 off / O1 white-list / O2 everything
        self.dtype = jnp.bfloat16    # TPU-native default (fp16 on request)
        self.white: Set[str] = set()
        self.black: Set[str] = set()
        # nan/inf sentry (FLAGS_check_nan_inf / amp.debugging tensor checker)
        self.check_nan_inf = False
        self.checker = None          # optional callable(op_name, leaves)


amp_state = AmpState()


def _cast_leaf(v, dtype):
    try:
        dt = v.dtype
    except AttributeError:
        return v
    if dt in (jnp.float32, jnp.float16, jnp.bfloat16) and dt != dtype:
        return v.astype(dtype)
    return v


def cast_dtype_for(op_name: Optional[str]):
    """The dtype the active policy casts `op_name` inputs to, or None."""
    st = amp_state
    if not st.enabled or op_name is None:
        return None
    if op_name in st.black:
        return jnp.float32
    if st.level == "O2" or op_name in st.white:
        return st.dtype
    return None


def maybe_cast_inputs(op_name: Optional[str], values):
    """Apply the active autocast policy to a flat list of raw op inputs."""
    dt = cast_dtype_for(op_name)
    if dt is None:
        return values
    return [_cast_leaf(v, dt) for v in values]
