from . import dtype, place, random
from .autograd import (
    enable_grad,
    is_grad_enabled,
    no_grad,
    run_backward,
    set_grad_enabled,
)
from .tensor import Tensor, is_tensor, to_tensor

__all__ = [
    "Tensor",
    "to_tensor",
    "is_tensor",
    "no_grad",
    "enable_grad",
    "set_grad_enabled",
    "is_grad_enabled",
    "run_backward",
    "dtype",
    "place",
    "random",
]
