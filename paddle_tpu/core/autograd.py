"""Define-by-run autograd engine over JAX eager ops.

TPU-native re-design of the reference eager autograd runtime
(``paddle/fluid/eager/backward.cc:104`` RunBackward, ``grad_node_info.h:168``
GradNodeBase): every differentiable op records a ``GradNode`` holding the
``jax.vjp`` pullback (the residuals play the role of the reference's
``TensorWrapper`` saved tensors). ``run_backward`` does the same queue-driven
reverse-topological traversal with pending-edge counts and gradient hooks.

On the hot path (jitted train step) none of this runs — ``paddle_tpu.jit``
traces pure functions and uses ``jax.grad`` directly, which is the TPU analog
of the reference's static-graph ``append_backward``.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.tree_util import tree_flatten, tree_unflatten

__all__ = [
    "GradNode",
    "no_grad",
    "enable_grad",
    "set_grad_enabled",
    "is_grad_enabled",
    "apply_op",
    "run_backward",
]


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_state = _GradState()


def is_grad_enabled() -> bool:
    return _state.enabled


@contextlib.contextmanager
def set_grad_enabled(mode: bool):
    prev = _state.enabled
    _state.enabled = bool(mode)
    try:
        yield
    finally:
        _state.enabled = prev


class _NoGrad(contextlib.ContextDecorator):
    """paddle.no_grad parity — usable as context manager and decorator."""

    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = False
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False


class _EnableGrad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = True
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False


no_grad = _NoGrad
enable_grad = _EnableGrad


class _Edge:
    """Snapshot of one producer edge at RECORD time.

    Edges must capture (node, out_idx) when the op is recorded, not
    dereference ``tensor._node`` during backward: in-place ops (``reshape_``,
    ``tanh_`` …) REBIND the python Tensor object to the new op's node, and a
    backward-time dereference would then see a self-edge and starve the
    traversal. The tensor ref is kept for hooks / capture / leaf-grad
    accumulation (identity semantics).
    """

    __slots__ = ("tensor", "node", "out_idx")

    def __init__(self, tensor):
        self.tensor = tensor
        self.node = tensor._node
        self.out_idx = tensor._out_idx


class GradNode:
    """One recorded op in the tape (≙ reference GradNodeBase, grad_node_info.h:168).

    Holds the vjp pullback, strong refs to parent Tensors via edge snapshots
    (keeps the graph alive the way TensorWrapper does), and the output
    structure needed to assemble cotangents.
    """

    __slots__ = (
        "vjp_fn",
        "parents",
        "out_treedef",
        "out_avals",
        "name",
        "consumed",
    )

    def __init__(self, vjp_fn, parents, out_treedef, out_avals, name=""):
        self.vjp_fn = vjp_fn
        # list[_Edge], order matches vjp cotangent outputs; producer node and
        # slot are frozen here (record time)
        self.parents = [p if isinstance(p, _Edge) else _Edge(p)
                        for p in parents]
        self.out_treedef = out_treedef
        self.out_avals = out_avals  # list[(shape, dtype)] per output leaf
        self.name = name
        self.consumed = False

    def __repr__(self):
        return f"GradNode({self.name}, n_out={len(self.out_avals)})"


def _is_tensor(x) -> bool:
    from .tensor import Tensor

    return isinstance(x, Tensor)


def apply_op(fn: Callable, *args, op_name: Optional[str] = None, **kwargs):
    """Execute ``fn`` on unwrapped values; record a GradNode if needed.

    ``fn`` is a pure jax-level function. Tensor leaves anywhere in
    (args, kwargs) are differentiable inputs; raw arrays / python scalars are
    constants. Returns Tensor-wrapped outputs mirroring fn's output pytree.
    """
    from . import op_hooks
    from .amp_state import _cast_leaf, cast_dtype_for
    from .tensor import Tensor

    if op_hooks.op_span_hook is not None:
        import time as _time

        _t0 = _time.perf_counter_ns()
        try:
            return _apply_op_inner(fn, args, kwargs, op_name)
        finally:
            op_hooks.op_span_hook(op_name or getattr(fn, "__name__", "op"),
                                  _t0, _time.perf_counter_ns())
    return _apply_op_inner(fn, args, kwargs, op_name)


def _apply_op_inner(fn, args, kwargs, op_name):
    from .amp_state import _cast_leaf, cast_dtype_for
    from .tensor import Tensor

    from ..static.program import static_state

    if static_state.enabled:
        from ..static.record import record_op

        return record_op(fn, args, kwargs, op_name)

    leaves, treedef = tree_flatten((args, kwargs), is_leaf=_is_tensor)
    t_idx = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
    raw = [l._value if isinstance(l, Tensor) else l for l in leaves]
    # autocast policy (≙ EagerAmpAutoCast in the generated ad_funcs,
    # eager_gen.py:462); only Tensor inputs are cast, not python scalars
    amp_dtype = cast_dtype_for(op_name)

    grad_wanted = _state.enabled and any(
        not leaves[i].stop_gradient for i in t_idx
    )

    if not grad_wanted:
        if amp_dtype is not None:
            for i in t_idx:
                raw[i] = _cast_leaf(raw[i], amp_dtype)
        a, k = tree_unflatten(treedef, raw)
        out = fn(*a, **k)
        _maybe_check_numerics(op_name, out)
        return _wrap_outputs(out, None)

    tvals = [raw[i] for i in t_idx]

    def _pure(*tv):
        # the cast happens INSIDE the differentiated function so the vjp
        # includes the cast-back edge: leaf grads arrive in the LEAF's dtype
        # (fp32 master grads for fp32 params under bf16/fp16 autocast),
        # matching the reference where the cast is itself a recorded op
        buf = list(raw)
        for i, v in zip(t_idx, tv):
            buf[i] = _cast_leaf(v, amp_dtype) if amp_dtype is not None else v
        a, k = tree_unflatten(treedef, buf)
        return fn(*a, **k)

    out, vjp_fn = jax.vjp(_pure, *tvals)
    _maybe_check_numerics(op_name, out)
    out_leaves, out_treedef = tree_flatten(out)
    out_avals = [(jnp.shape(o), jnp.result_type(o)) for o in out_leaves]
    node = GradNode(
        vjp_fn,
        [leaves[i] for i in t_idx],
        out_treedef,
        out_avals,
        name=op_name or getattr(fn, "__name__", "op"),
    )
    return _wrap_outputs(out, node)


def _maybe_check_numerics(op_name, out):
    """Post-op nan/inf sentry (≙ CheckTensorHasNanOrInf after every eager op,
    eager/nan_inf_utils.cc:83, gated by FLAGS_check_nan_inf). Only scans
    concrete values — under trace it would force materialisation."""
    from .amp_state import amp_state

    if not (amp_state.check_nan_inf or amp_state.checker is not None):
        return
    leaves = [o for o in tree_flatten(out)[0] if hasattr(o, "dtype")]
    leaves = [o for o in leaves
              if not isinstance(o, jax.core.Tracer)
              and jnp.issubdtype(o.dtype, jnp.inexact)]
    if not leaves:
        return
    if amp_state.checker is not None:
        amp_state.checker(op_name or "op", leaves)
    if amp_state.check_nan_inf:
        for o in leaves:
            bad = int(jnp.sum(~jnp.isfinite(o)))
            if bad:
                raise RuntimeError(
                    f"Operator {op_name or 'op'} output contains {bad} "
                    f"Nan/Inf element(s) (FLAGS_check_nan_inf)")


def _wrap_outputs(out, node):
    from .tensor import Tensor

    out_leaves, out_treedef = tree_flatten(out)
    wrapped = []
    for i, o in enumerate(out_leaves):
        t = Tensor(o, stop_gradient=(node is None))
        if node is not None:
            t._node = node
            t._out_idx = i
        wrapped.append(t)
    res = tree_unflatten(out_treedef, wrapped)
    return res


# ---------------------------------------------------------------------------
# Backward traversal (≙ egr::RunBackward, eager/backward.cc:104)
# ---------------------------------------------------------------------------


def _ones_like(value):
    return jnp.ones(jnp.shape(value), jnp.result_type(value))


def _place_leaf_grad(t, g):
    """ZeRO-2: a param tagged with ``grad_pspec`` (GroupShardedStage2) gets
    its eager .grad placed SHARDED over the sharding axis at accumulation
    time — the eager analog of reduce-scatter-into-the-owner-shard. No-op
    for untagged params and under trace (jit grads are placed by
    in_shardings)."""
    spec = getattr(t, "grad_pspec", None)
    if spec is None or isinstance(g, jax.core.Tracer):
        return g
    from ..distributed._spmd import named_sharding

    try:
        return jax.device_put(g, named_sharding(spec))
    except (RuntimeError, ValueError):
        return g  # spec/mesh mismatch (e.g. mesh rebuilt smaller): keep global


def _zero_cotangent(shape, dtype):
    import numpy as _np

    if not jnp.issubdtype(dtype, jnp.floating) and not jnp.issubdtype(
        dtype, jnp.complexfloating
    ):
        return _np.zeros(shape, jax.dtypes.float0)
    return jnp.zeros(shape, dtype)


def run_backward(
    tensors: Sequence[Any],
    grad_tensors: Optional[Sequence[Any]] = None,
    retain_graph: bool = False,
    capture: Optional[Sequence[Any]] = None,
    accumulate_leaf_grads: bool = True,
    allow_unused: bool = True,
):
    """Reverse-mode traversal from ``tensors`` seeding ``grad_tensors``.

    If ``capture`` is given, returns the gradient arrays for those tensors
    (paddle.grad path, ≙ GeneralGrad eager/backward.cc:102); otherwise
    accumulates ``.grad`` on reachable leaves (loss.backward path).
    """
    from .tensor import Tensor

    tensors = list(tensors)
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    grad_tensors = list(grad_tensors)
    if len(grad_tensors) != len(tensors):
        raise ValueError("grad_tensors length mismatch")

    capture_ids = None
    captured: Dict[int, Any] = {}
    if capture is not None:
        capture_ids = {id(t): i for i, t in enumerate(capture)}

    # cotangent buffers: per-node list of per-output cotangents, plus direct
    # per-tensor accumulation for leaves (GradTensorHolder analog).
    node_cots: Dict[int, List[Optional[Any]]] = {}
    nodes: Dict[int, GradNode] = {}

    def _seed(t: Tensor, g):
        if g is None:
            if jnp.size(t._value) != 1:
                raise RuntimeError(
                    "grad must be provided for non-scalar backward seed"
                )
            g = _ones_like(t._value)
        elif isinstance(g, Tensor):
            g = g._value
        # seeds route to the tensor's CURRENT producer (the user backwards
        # from the value as it stands now); recorded edges use snapshots
        _route(t, g, t._node, t._out_idx)

    def _route(t: Tensor, g, node, out_idx):
        """Deliver cotangent g to tensor t: hooks, capture, leaf accum, node slot."""
        if getattr(g, "dtype", None) == jax.dtypes.float0:
            return  # integer/bool primal path — no gradient flows
        for hook in t._hooks:
            out = hook(Tensor(g, stop_gradient=True))
            if out is not None:
                g = out._value if isinstance(out, Tensor) else out
        if capture_ids is not None and id(t) in capture_ids:
            prev = captured.get(id(t))
            captured[id(t)] = g if prev is None else prev + g
        if node is not None and node.consumed and id(node) not in nodes:
            raise RuntimeError(
                "Trying to backward through a graph that was already freed; "
                "set retain_graph=True on the first backward"
            )
        if node is None or node.consumed:
            if accumulate_leaf_grads and not t.stop_gradient and node is None:
                g = _place_leaf_grad(t, g)
                if t.grad is None:
                    t.grad = Tensor(g, stop_gradient=True)
                else:
                    t.grad = Tensor(t.grad._value + g, stop_gradient=True)
            return
        nid = id(node)
        nodes[nid] = node
        slots = node_cots.setdefault(nid, [None] * len(node.out_avals))
        idx = out_idx
        # autocast boundaries: a black-list op (fp32) consuming a white-list
        # output (bf16) sends an fp32 cotangent to a bf16 output — cast to
        # the primal's dtype, as the reference's AMP grads follow param dtype
        exp_dtype = node.out_avals[idx][1]
        if getattr(g, "dtype", exp_dtype) != exp_dtype and jnp.issubdtype(
                exp_dtype, jnp.inexact):
            g = g.astype(exp_dtype)
        slots[idx] = g if slots[idx] is None else slots[idx] + g
        if t._retain_grad and accumulate_leaf_grads:
            if t.grad is None:
                t.grad = Tensor(g, stop_gradient=True)
            else:
                t.grad = Tensor(t.grad._value + g, stop_gradient=True)

    # --- discover reachable graph, count child->parent edges per node ------
    pending: Dict[int, int] = {}
    seen = set()
    stack = [t._node for t in tensors if isinstance(t, Tensor) and t._node is not None]
    stack = [n for n in stack if not n.consumed]
    for n in stack:
        nodes[id(n)] = n
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        for e in n.parents:
            pn = e.node
            if pn is not None and not pn.consumed:
                pending[id(pn)] = pending.get(id(pn), 0) + 1
                nodes[id(pn)] = pn
                if id(pn) not in seen:
                    stack.append(pn)

    # --- seed -------------------------------------------------------------
    for t, g in zip(tensors, grad_tensors):
        if not isinstance(t, Tensor):
            raise TypeError("backward expects Tensors")
        _seed(t, g)

    # --- Kahn queue over nodes whose children have all fired ---------------
    # A node whose pending count hits zero with NO cotangent slots (all its
    # outputs' gradients were float0 / dead) must still release its parents'
    # pending edges, else ancestors starve (e.g. an int-cast side branch off
    # a shared float subgraph).
    executed = set()
    ready = []

    def _release_dead(node):
        stack_ = [node]
        while stack_:
            n = stack_.pop()
            n.consumed = n.consumed or not retain_graph
            for e in n.parents:
                pn = e.node
                if pn is None:
                    continue
                pid = id(pn)
                if pid in pending:
                    pending[pid] -= 1
                    if pending[pid] == 0 and pid not in executed:
                        if pid in node_cots:
                            ready.append(pn)
                        else:
                            executed.add(pid)
                            stack_.append(pn)

    ready.extend(nodes[nid] for nid in node_cots if pending.get(nid, 0) == 0)
    for nid, n in list(nodes.items()):
        if pending.get(nid, 0) == 0 and nid not in node_cots and nid not in executed:
            # seeded-dead root (all seeds float0) — release immediately
            executed.add(nid)
            _release_dead(n)
    while ready:
        node = ready.pop()
        nid = id(node)
        if nid in executed:
            continue
        executed.add(nid)
        slots = node_cots.get(nid)
        if slots is None:
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                f"Trying to backward through node {node.name} a second time; "
                "set retain_graph=True on the first backward"
            )
        cots = [
            s if s is not None else _zero_cotangent(shape, dtype)
            for s, (shape, dtype) in zip(slots, node.out_avals)
        ]
        cot_tree = tree_unflatten(node.out_treedef, cots)
        parent_grads = node.vjp_fn(cot_tree)
        if not retain_graph:
            node.vjp_fn = None
            node.consumed = True
        for e, pg in zip(node.parents, parent_grads):
            _route(e.tensor, pg, e.node, e.out_idx)
            pn = e.node
            if pn is not None:
                pid = id(pn)
                if pid in pending:
                    pending[pid] -= 1
                    if pending[pid] == 0 and pid not in executed:
                        if pid in node_cots:
                            ready.append(pn)
                        else:
                            executed.add(pid)
                            _release_dead(pn)

    if capture_ids is not None:
        out = []
        for t in capture:
            g = captured.get(id(t))
            if g is None and not allow_unused:
                raise RuntimeError("One of the differentiated tensors was unused")
            out.append(None if g is None else Tensor(g, stop_gradient=True))
        return out
    return None
