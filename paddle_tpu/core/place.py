"""Device / place abstraction.

TPU-native analog of the reference's ``phi::Place`` (paddle/phi/common/place.h)
and device management (``phi/backends/device_manager.h:294``). On TPU the
"place" maps to a ``jax.Device``; there is no per-op stream management — XLA
owns scheduling.
"""
from __future__ import annotations

import functools

import jax


class Place:
    """A logical device. Compares by (kind, index)."""

    kind = "unknown"

    def __init__(self, index: int = 0):
        self.index = int(index)

    def __eq__(self, other):
        return isinstance(other, Place) and (self.kind, self.index) == (
            other.kind,
            other.index,
        )

    def __hash__(self):
        return hash((self.kind, self.index))

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def jax_device(self):
        devs = _devices_of_kind(self.kind)
        if not devs:
            raise RuntimeError(f"no {self.kind} devices visible to JAX")
        return devs[self.index % len(devs)]


class CPUPlace(Place):
    kind = "cpu"


class TPUPlace(Place):
    kind = "tpu"


class CUDAPlace(Place):
    # API-compat alias: reference code uses CUDAPlace; maps to accelerator 0..n.
    kind = "tpu"


@functools.lru_cache(maxsize=None)
def _devices_of_kind(kind: str):
    if kind == "cpu":
        try:
            return tuple(jax.devices("cpu"))
        except RuntimeError:
            return tuple()
    accel = [d for d in jax.devices() if d.platform != "cpu"]
    return tuple(accel) if accel else tuple(jax.devices())


_current_device = [None]


def set_device(device: str):
    """paddle.set_device parity (python/paddle/device/__init__.py)."""
    if ":" in device:
        kind, idx = device.split(":", 1)
        idx = int(idx)
    else:
        kind, idx = device, 0
    kind = {"gpu": "tpu", "cuda": "tpu", "xpu": "tpu"}.get(kind, kind)
    place = CPUPlace(idx) if kind == "cpu" else TPUPlace(idx)
    _current_device[0] = place
    return place


def get_device() -> str:
    p = _current_place()
    return f"{p.kind}:{p.index}"


def _current_place() -> Place:
    if _current_device[0] is None:
        default = jax.devices()[0]
        _current_device[0] = (
            CPUPlace(0) if default.platform == "cpu" else TPUPlace(0)
        )
    return _current_device[0]


def is_compiled_with_cuda() -> bool:  # API parity; always False on TPU build
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform != "cpu" for d in jax.devices())


def device_count() -> int:
    return len(jax.devices())


class CUDAPinnedPlace(Place):
    """API-compat alias (reference pinned-host memory place); host memory is
    uniformly managed by JAX on TPU, so this is a tagged CPUPlace."""

    def __init__(self):
        super().__init__("cpu", 0)

    def __repr__(self):
        return "CUDAPinnedPlace"
