"""RNG state management.

TPU-native analog of the reference's ``phi::Generator`` (phi/core/generator.h):
a named-stream counter-based design over JAX PRNG keys. Eager ops fold a
monotonically increasing counter into the seed key; under ``paddle_tpu.jit``
tracing, a traced key can be pushed so randomness varies per step inside a
compiled function (the reference achieves this with stateful curand;
functional keys are the XLA-friendly form).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax

__all__ = ["Generator", "default_generator", "seed", "get_rng_state",
           "set_rng_state", "trace_key_scope"]


class Generator:
    def __init__(self, seed_: int = 0):
        self._seed = seed_
        self._counter = 0

    def manual_seed(self, seed_: int):
        self._seed = int(seed_)
        self._counter = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def get_state(self):
        return (self._seed, self._counter)

    def set_state(self, state):
        self._seed, self._counter = int(state[0]), int(state[1])

    def next_key(self):
        tk = _trace_key.value
        if tk is not None:
            # inside a traced/jitted region: derive from the traced key so the
            # compiled program gets fresh randomness every invocation
            sub = jax.random.fold_in(tk, _trace_key.bump())
            return sub
        self._counter += 1
        return jax.random.fold_in(jax.random.PRNGKey(self._seed), self._counter)


class _TraceKey(threading.local):
    def __init__(self):
        self.value = None
        self.n = 0

    def bump(self):
        self.n += 1
        return self.n


_trace_key = _TraceKey()


@contextlib.contextmanager
def trace_key_scope(key):
    prev, prev_n = _trace_key.value, _trace_key.n
    _trace_key.value, _trace_key.n = key, 0
    try:
        yield
    finally:
        _trace_key.value, _trace_key.n = prev, prev_n


default_generator = Generator(0)


def seed(s: int):
    """paddle.seed parity (python/paddle/framework/random.py)."""
    default_generator.manual_seed(s)
    return default_generator


def get_rng_state():
    return [default_generator.get_state()]


def set_rng_state(state):
    default_generator.set_state(state[0])
