"""Op-dispatch instrumentation point.

The reference emits RecordEvent spans inside every generated ad_func
(eager_gen.py:1097-1098); here the single choke point is apply_op, which
calls ``op_span_hook(name, start_ns, end_ns)`` when one is installed.
None = zero overhead. Two consumers exist — the profiler (trace spans)
and the monitor (latency histograms) — and both install by saving the
previous hook and chaining to it, so they compose in either order.
"""
from __future__ import annotations

from typing import Callable, Optional

op_span_hook: Optional[Callable[[str, int, int], None]] = None

# Chain protocol shared by the consumers: a hook that saves the previous
# slot value and forwards to it exposes it as ``hook.prev_hook``; a hook
# that can go permanently dead (a stopped profiler window stranded under
# another consumer) flags itself with ``hook.armed = False``. Installers
# and restorers prune dead links with skip_dead so chains never regrow
# across profile/monitor interleaves.


def skip_dead(hook):
    """Follow ``prev_hook`` links past hooks whose ``armed`` flag is
    False; returns the first live hook (or None)."""
    while hook is not None and not getattr(hook, "armed", True):
        hook = getattr(hook, "prev_hook", None)
    return hook
