"""Op-dispatch instrumentation point.

The reference emits RecordEvent spans inside every generated ad_func
(eager_gen.py:1097-1098); here the single choke point is apply_op, which
calls ``op_span_hook(name, start_ns, end_ns)`` when one is installed (the
profiler does). None = zero overhead.
"""
from __future__ import annotations

from typing import Callable, Optional

op_span_hook: Optional[Callable[[str, int, int], None]] = None
