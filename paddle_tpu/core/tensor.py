"""Tensor facade over ``jax.Array``.

TPU-native analog of the reference's ``phi::DenseTensor``
(paddle/phi/core/dense_tensor.h:43) + eager tensor (pybind/eager_method.cc:101):
a thin wrapper holding a jax array, the ``stop_gradient`` flag, an optional
``.grad``, and a pointer into the autograd tape (GradNode). Device placement,
layout and allocation are owned by JAX/XLA — there is no Place/Allocator
plumbing to re-implement per op.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes
from .autograd import apply_op, is_grad_enabled, no_grad, run_backward
from .place import CPUPlace, Place, TPUPlace, _current_place

__all__ = ["Tensor", "to_tensor", "is_tensor"]


class Tensor:
    __slots__ = (
        "_value",
        "stop_gradient",
        "grad",
        "name",
        "persistable",
        "_node",
        "_out_idx",
        "_hooks",
        "_retain_grad",
        "pspec",
        "__weakref__",
    )

    def __init__(self, value, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(value, Tensor):
            value = value._value
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self.name = name
        self.persistable = False
        self._node = None
        self._out_idx = 0
        self._hooks = []
        self._retain_grad = False

    # -- interop -----------------------------------------------------------
    def __jax_array__(self):
        """Allow jnp.* functions to consume Tensor directly."""
        return self._value

    @property
    def value(self):
        return self._value

    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def item(self, *args):
        return np.asarray(self._value).item(*args)

    def tolist(self):
        return np.asarray(self._value).tolist()

    # -- metadata ----------------------------------------------------------
    @property
    def shape(self):
        return list(jnp.shape(self._value))

    @property
    def ndim(self) -> int:
        return jnp.ndim(self._value)

    def dim(self) -> int:
        return self.ndim

    def rank(self) -> int:
        return self.ndim

    @property
    def size(self) -> int:
        return int(np.prod(jnp.shape(self._value), dtype=np.int64))

    def numel(self) -> int:
        return self.size

    @property
    def dtype(self):
        return jnp.result_type(self._value)

    @property
    def place(self) -> Place:
        devs = getattr(self._value, "devices", None)
        if callable(devs):
            try:
                d = next(iter(self._value.devices()))
                return CPUPlace(d.id) if d.platform == "cpu" else TPUPlace(d.id)
            except Exception:
                pass
        return _current_place()

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    def __len__(self):
        s = jnp.shape(self._value)
        if not s:
            raise TypeError("len() of a 0-d tensor")
        return s[0]

    def __repr__(self):
        grad_tag = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}{grad_tag},\n"
            f"       {np.asarray(self._value)!r})"
        )

    def __bool__(self):
        return bool(np.asarray(self._value))

    def __int__(self):
        return int(np.asarray(self._value))

    def __float__(self):
        return float(np.asarray(self._value))

    def __format__(self, spec):
        if self.ndim == 0:
            return format(np.asarray(self._value).item(), spec)
        return object.__format__(self, spec)

    def __hash__(self):
        return id(self)

    # -- autograd ----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        """loss.backward() parity (eager/backward.cc:104)."""
        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def register_hook(self, hook):
        self._hooks.append(hook)

        class _Removable:
            def remove(_self):
                try:
                    self._hooks.remove(hook)
                except ValueError:
                    pass

        return _Removable()

    def retain_grads(self):
        self._retain_grad = True

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero: bool = False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._value), stop_gradient=True)
        else:
            self.grad = None

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def detach_(self) -> "Tensor":
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        return apply_op(jnp.copy, self, op_name="clone")

    # -- dtype / device ----------------------------------------------------
    def astype(self, dtype) -> "Tensor":
        d = dtypes.convert_dtype(dtype)
        return apply_op(lambda v: v.astype(d), self, op_name="cast")

    def cast(self, dtype) -> "Tensor":
        return self.astype(dtype)

    def cpu(self) -> "Tensor":
        cpu_dev = jax.devices("cpu")[0]
        # device_put is a differentiable jax primitive — keep the tape intact
        return apply_op(
            lambda v: jax.device_put(v, cpu_dev), self, op_name="to_cpu"
        )

    def to(self, *args, **kwargs) -> "Tensor":
        device = kwargs.get("device")
        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, str) and (a in dtypes._NAME_TO_DTYPE):
                dtype = a
            elif isinstance(a, str) or isinstance(a, Place):
                device = a
        out = self
        if dtype is not None:
            out = out.astype(dtype)
        if device is not None:
            place = device if isinstance(device, Place) else _parse_place(device)
            dev = place.jax_device()
            out = apply_op(
                lambda v: jax.device_put(v, dev), out, op_name="to_device"
            )
        return out

    def pin_memory(self):  # no-op on TPU; host staging is XLA's job
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # -- in-place (optimizer path; guarded against tape corruption) --------
    def _inplace_(self, new_value) -> "Tensor":
        if self._node is not None and is_grad_enabled():
            raise RuntimeError(
                "in-place update on a tensor recorded by autograd; wrap in no_grad()"
            )
        if isinstance(new_value, Tensor):
            new_value = new_value._value
        self._value = jnp.asarray(new_value, dtype=self.dtype)
        return self

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        self._value = jnp.asarray(value)
        return self

    def copy_(self, other, *args):
        return self._inplace_(other)

    def fill_(self, v):
        return self._inplace_(jnp.full_like(self._value, v))

    def zero_(self):
        return self._inplace_(jnp.zeros_like(self._value))

    def add_(self, other):
        return self._inplace_(self._value + _unwrap(other))

    def subtract_(self, other):
        return self._inplace_(self._value - _unwrap(other))

    def multiply_(self, other):
        return self._inplace_(self._value * _unwrap(other))

    def scale_(self, s, bias: float = 0.0):
        return self._inplace_(self._value * s + bias)

    def clip_(self, min=None, max=None):
        return self._inplace_(jnp.clip(self._value, min, max))

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, idx) -> "Tensor":
        idx = _unwrap_index(idx)
        return apply_op(lambda v: v[idx], self, op_name="getitem")

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)
        value = _unwrap(value)
        if self._node is not None and is_grad_enabled():
            raise RuntimeError(
                "in-place __setitem__ on a non-leaf autograd tensor is not "
                "supported; use paddle_tpu.scatter / tensor.at-style ops"
            )
        self._value = jnp.asarray(self._value).at[idx].set(value)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- python protocol: arithmetic dunders wired in ops/_methods.py ------


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _unwrap_index(idx):
    if isinstance(idx, tuple):
        return tuple(_unwrap(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray([_unwrap(i) for i in idx])
    return _unwrap(idx)


def _parse_place(device: str) -> Place:
    if ":" in device:
        kind, idx = device.split(":", 1)
        idx = int(idx)
    else:
        kind, idx = device, 0
    kind = {"gpu": "tpu", "cuda": "tpu"}.get(kind, kind)
    return CPUPlace(idx) if kind == "cpu" else TPUPlace(idx)


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """paddle.to_tensor parity (python/paddle/tensor/creation.py)."""
    d = dtypes.convert_dtype(dtype)
    if isinstance(data, Tensor):
        value = data._value
    else:
        value = data
    if d is None and not hasattr(value, "dtype"):
        # python scalars / lists follow paddle's defaults: float->default dtype
        arr = np.asarray(value)
        if arr.dtype == np.float64:
            d = dtypes.get_default_dtype()
        elif arr.dtype == np.int64:
            d = dtypes.int64
    value = jnp.asarray(value, dtype=d)
    if place is not None:
        p = place if isinstance(place, Place) else _parse_place(str(place))
        value = jax.device_put(value, p.jax_device())
    return Tensor(value, stop_gradient=stop_gradient)


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


# Register Tensor as a pytree so jax transforms can consume containers of them.
jax.tree_util.register_pytree_node(
    Tensor,
    lambda t: ((t._value,), (t.stop_gradient, t.name)),
    lambda aux, children: Tensor(children[0], stop_gradient=aux[0], name=aux[1]),
)
