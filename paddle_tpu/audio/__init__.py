"""paddle.audio parity (reference: python/paddle/audio/__init__.py):
features, functional, datasets, backends (stdlib-wave default), load/save.
"""
from . import backends, datasets, features, functional
from .backends import info, load, save

__all__ = ["functional", "features", "datasets", "backends", "load", "save",
           "info"]
