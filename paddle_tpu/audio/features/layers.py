"""Audio feature layers (reference: python/paddle/audio/features/layers.py
— Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC as nn.Layers over the
framework stft).
"""
from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn import Layer
from ...ops._helpers import unwrap
from ..functional import (compute_fbank_matrix, create_dct, power_to_db)
from ..functional.window import get_window

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    """|STFT|^power (reference layers.py:31)."""

    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None,
                 window: Union[str, tuple] = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 dtype: str = "float32"):
        super().__init__()
        if power <= 0:
            raise ValueError("power must be positive")
        self.power = power
        if win_length is None:
            win_length = n_fft
        self.n_fft = n_fft
        self.hop_length = hop_length or win_length // 4
        self.win_length = win_length
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = jnp.asarray(
            unwrap(get_window(window, win_length, fftbins=True,
                              dtype="float64"))).astype(dtype)

    def forward(self, x):
        from ... import signal

        stft = signal.stft(x, n_fft=self.n_fft, hop_length=self.hop_length,
                           win_length=self.win_length,
                           window=Tensor(self.fft_window),
                           center=self.center, pad_mode=self.pad_mode)
        spect = jnp.abs(unwrap(stft)) ** self.power
        return Tensor(spect)


class MelSpectrogram(Layer):
    """Mel-scaled spectrogram (reference layers.py:124)."""

    def __init__(self, sr: int = 22050, n_fft: int = 2048,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None,
                 window: Union[str, tuple] = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0,
                 f_max: Optional[float] = None, htk: bool = False,
                 norm: Union[str, float] = "slaney",
                 dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.n_mels = n_mels
        self.f_min = f_min
        self.f_max = f_max
        self.htk = htk
        self.norm = norm
        if f_max is None:
            f_max = sr // 2
        self.fbank_matrix = unwrap(compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype))

    def forward(self, x):
        spect = unwrap(self._spectrogram(x))
        mel = jnp.matmul(self.fbank_matrix, spect)
        return Tensor(mel)


class LogMelSpectrogram(Layer):
    """log-dB mel spectrogram (reference layers.py:243)."""

    def __init__(self, sr: int = 22050, n_fft: int = 2048,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None,
                 window: Union[str, tuple] = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0,
                 f_max: Optional[float] = None, htk: bool = False,
                 norm: Union[str, float] = "slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 dtype: str = "float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return power_to_db(mel, ref_value=self.ref_value, amin=self.amin,
                           top_db=self.top_db)


class MFCC(Layer):
    """Mel-frequency cepstral coefficients (reference layers.py:385)."""

    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 2048,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None,
                 window: Union[str, tuple] = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0,
                 f_max: Optional[float] = None, htk: bool = False,
                 norm: Union[str, float] = "slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 dtype: str = "float32"):
        super().__init__()
        if n_mfcc > n_mels:
            raise ValueError("n_mfcc cannot be larger than n_mels")
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.dct_matrix = unwrap(create_dct(n_mfcc=n_mfcc, n_mels=n_mels,
                                            dtype=dtype))

    def forward(self, x):
        logmel = unwrap(self._log_melspectrogram(x))
        mfcc = jnp.matmul(jnp.swapaxes(logmel, -1, -2),
                          self.dct_matrix)
        return Tensor(jnp.swapaxes(mfcc, -1, -2))
