"""Backend registry (reference: python/paddle/audio/backends/backend.py —
get_current_backend/list_available_backends/set_backend dispatch).
"""
from __future__ import annotations

from typing import List

from . import wave_backend as _wave
from .wave_backend import AudioInfo

__all__ = ["get_current_backend", "list_available_backends", "set_backend",
           "load", "save", "AudioInfo"]

_BACKENDS = {"wave_backend": _wave}
_current = ["wave_backend"]


def list_available_backends() -> List[str]:
    return sorted(_BACKENDS)


def get_current_backend() -> str:
    return _current[0]


def set_backend(backend_name: str):
    if backend_name not in _BACKENDS:
        raise NotImplementedError(
            f"backend {backend_name!r} not available (have "
            f"{list_available_backends()}; soundfile is not bundled in the "
            "TPU image)")
    _current[0] = backend_name


def load(*args, **kwargs):
    return _BACKENDS[_current[0]].load(*args, **kwargs)


def save(*args, **kwargs):
    return _BACKENDS[_current[0]].save(*args, **kwargs)


def info(*args, **kwargs):
    return _BACKENDS[_current[0]].info(*args, **kwargs)
