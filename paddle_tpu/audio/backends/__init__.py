"""Audio I/O backends (reference: python/paddle/audio/backends/ —
wave_backend.py default + pluggable soundfile backend).

TPU-native/zero-dep: the default backend reads and writes PCM WAV via the
stdlib ``wave`` module (exactly the reference's fallback wave_backend).
"""
from . import wave_backend
from .backend import (AudioInfo, get_current_backend,
                      list_available_backends, load, save, set_backend)

__all__ = ["get_current_backend", "list_available_backends", "set_backend",
           "load", "save", "AudioInfo", "info", "wave_backend"]

info = wave_backend.info
