"""PCM WAV read/write over the stdlib wave module (reference:
python/paddle/audio/backends/wave_backend.py).
"""
from __future__ import annotations

import wave
from typing import Optional, Tuple

import numpy as np

from ...core.tensor import Tensor

__all__ = ["load", "save", "info"]

_WIDTH_DTYPE = {1: np.uint8, 2: np.int16, 4: np.int32}


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath: str) -> AudioInfo:
    with wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8)


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True
         ) -> Tuple[Tensor, int]:
    """Returns (waveform [C, T] (or [T, C]), sample_rate)."""
    with wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    dtype = _WIDTH_DTYPE.get(width)
    if dtype is None:
        raise ValueError(f"unsupported sample width {width}")
    data = np.frombuffer(raw, dtype=dtype).reshape(-1, nch)
    if width == 1:  # uint8 is offset-binary
        data = data.astype(np.int16) - 128
        scale = 128.0
    else:
        scale = float(2 ** (width * 8 - 1))
    if normalize:
        data = data.astype(np.float32) / scale
    if channels_first:
        data = data.T
    return Tensor(np.ascontiguousarray(data)), sr


def save(filepath: str, src, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_S", bits_per_sample: int = 16):
    """Write PCM WAV. src: Tensor/ndarray [C, T] (or [T, C])."""
    data = np.asarray(src.numpy() if hasattr(src, "numpy") else src)
    if data.ndim == 1:
        data = data[None] if channels_first else data[:, None]
    if channels_first:
        data = data.T                                   # [T, C]
    if bits_per_sample != 16:
        raise ValueError("only 16-bit PCM save is supported")
    if np.issubdtype(data.dtype, np.floating):
        data = np.clip(data, -1.0, 1.0)
        data = (data * 32767.0).astype(np.int16)
    with wave.open(filepath, "wb") as f:
        f.setnchannels(data.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(data.astype("<i2").tobytes())
